"""Chaos-schedule tests: replica kills, outages, failure propagation.

Semantics under test (mirroring the reference's behavior when its chaos
CronJobs kill components): a fully-down callee is a *transport* error, so
the caller stops at the failing step and returns 500 upward
(srv/handler.go:66-76) — while plain downstream 500s do not propagate
(executable.go:132-143); concurrent siblings of a failing call still run
(executable.go:148-179, goroutines are all launched before the join).
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import ChaosEvent

KEY = jax.random.PRNGKey(5)
DET = SimParams(service_time="deterministic")
CPU = DET.cpu_time_s
RTT1 = 2 * DET.network.base_latency_s

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  script:
  - sleep: 10ms
  - call: mid
  - sleep: 50ms
- name: mid
"""


def run_chain(chaos, n=4000, qps=20.0, yaml=CHAIN):
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    sim = Simulator(compiled, DET, chaos)
    return sim.run(LoadModel(kind="open", qps=qps), n, KEY)


def test_outage_window_errors_propagate_to_client():
    # ~200s of traffic; mid fully down in [50, 100) => ~25% client errors
    res = run_chain([ChaosEvent("mid", 50.0, 100.0)])
    starts = np.asarray(res.client_start)
    err = np.asarray(res.client_error)
    in_window = (starts >= 50.0) & (starts < 100.0)
    assert err[in_window].all()
    assert not err[~in_window].any()
    # down callee is never executed in the window
    sent_mid = np.asarray(res.hop_sent[:, 1])
    assert not sent_mid[in_window].any()
    assert sent_mid[~in_window].all()


def test_failure_truncates_script_at_failing_step():
    res = run_chain([ChaosEvent("mid", 50.0, 100.0)])
    starts = np.asarray(res.client_start)
    lat = np.asarray(res.client_latency)
    in_window = (starts >= 50.0) & (starts < 100.0)
    # healthy: 10ms + (rtt + cpu) + 50ms; failed: the 10ms sleep ran, the
    # failing call cost ~nothing, the trailing 50ms sleep was skipped.
    # (medians: rare queueing waits perturb a fraction of samples)
    healthy = RTT1 + CPU + 0.010 + (RTT1 + CPU) + 0.050
    failed = RTT1 + CPU + 0.010
    assert np.median(lat[~in_window]) == pytest.approx(healthy, rel=1e-4)
    assert np.median(lat[in_window]) == pytest.approx(failed, rel=1e-4)


def test_concurrent_sibling_of_failing_call_still_runs():
    yaml = """
services:
- name: entry
  isEntrypoint: true
  script:
  - - call: down
    - call: slow
- name: down
- name: slow
  script:
  - sleep: 30ms
"""
    res = run_chain([ChaosEvent("down", 0.0, 1e6)], yaml=yaml)
    # every request fails (down is always down) but the slow sibling runs
    assert np.asarray(res.client_error).all()
    assert np.asarray(res.hop_sent[:, 2]).all()  # slow
    assert not np.asarray(res.hop_sent[:, 1]).any()  # down
    want = RTT1 + CPU + (RTT1 + CPU + 0.030)
    assert np.median(res.client_latency) == pytest.approx(want, rel=1e-4)


def test_transport_error_propagates_only_one_level():
    # grandparent -> parent -> down: parent 500s (transport), but parent's
    # 500 is a valid HTTP response, so grandparent succeeds.
    yaml = """
services:
- name: top
  isEntrypoint: true
  script:
  - call: parent
- name: parent
  script:
  - call: dead
- name: dead
"""
    res = run_chain([ChaosEvent("dead", 0.0, 1e6)], yaml=yaml)
    assert not np.asarray(res.client_error).any()
    assert np.asarray(res.hop_error[:, 1]).all()  # parent 500s


def test_partial_replica_kill_raises_tail_latency():
    yaml = """
services:
- name: solo
  isEntrypoint: true
  numReplicas: 4
"""
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    # losing 3 of 4 replicas pushes the survivor to rho=0.9 in-window
    qps = 0.9 / SimParams().cpu_time_s
    sim = Simulator(
        compiled,
        SimParams(service_time="exponential"),
        [ChaosEvent("solo", 20.0, 40.0, replicas_down=3)],
    )
    # enough requests that the stream spans well past the [20, 40) window
    res = sim.run(LoadModel(kind="open", qps=qps), 700_000, KEY)
    starts = np.asarray(res.client_start)
    lat = np.asarray(res.client_latency)
    inside = lat[(starts >= 20.0) & (starts < 40.0)]
    outside = lat[(starts < 20.0) | (starts >= 40.0)]
    assert not np.asarray(res.client_error).any()  # degraded, not down
    assert np.quantile(inside, 0.99) > 3 * np.quantile(outside, 0.99)
    # utilization reports the worst phase
    assert float(res.utilization[0]) == pytest.approx(0.9, rel=1e-3)
    assert not bool(res.unstable[0])


def test_chaos_validation():
    with pytest.raises(ValueError):
        ChaosEvent("x", 10.0, 10.0)
    with pytest.raises(ValueError):
        ChaosEvent("x", -1.0, 10.0)
    with pytest.raises(ValueError):
        ChaosEvent("x", 0.0, 10.0, replicas_down=0)
    compiled = compile_graph(
        ServiceGraph.from_yaml("services:\n- name: a\n  isEntrypoint: true\n")
    )
    with pytest.raises(ValueError, match="unknown service"):
        Simulator(compiled, chaos=[ChaosEvent("ghost", 0.0, 1.0)])


def test_no_chaos_unchanged_semantics():
    res = run_chain([])
    assert not np.asarray(res.client_error).any()
    want = RTT1 + CPU + 0.010 + (RTT1 + CPU) + 0.050
    assert np.median(res.client_latency) == pytest.approx(want, rel=1e-4)


def test_entry_outage_refuses_client_connections():
    # chaos on the entrypoint itself: the client's connection is refused —
    # client errors, nothing executes, latency is one wire round trip.
    yaml = "services:\n- name: entry\n  isEntrypoint: true\n  script:\n  - sleep: 20ms\n"
    res = run_chain([ChaosEvent("entry", 50.0, 100.0)], yaml=yaml)
    starts = np.asarray(res.client_start)
    err = np.asarray(res.client_error)
    sent = np.asarray(res.hop_sent[:, 0])
    in_window = (starts >= 50.0) & (starts < 100.0)
    assert err[in_window].all() and not err[~in_window].any()
    assert not sent[in_window].any() and sent[~in_window].all()
    lat = np.asarray(res.client_latency)
    assert np.median(lat[in_window]) == pytest.approx(RTT1, rel=1e-3)
    assert np.median(lat[~in_window]) == pytest.approx(
        RTT1 + CPU + 0.020, rel=1e-3
    )


def test_down_service_reports_zero_utilization():
    # numReplicas=4 at rho=0.5; total outage must NOT report saturation
    yaml = "services:\n- name: solo\n  isEntrypoint: true\n  numReplicas: 4\n"
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    qps = 2.0 / SimParams().cpu_time_s  # rho = 0.5 across 4 replicas
    sim = Simulator(compiled, DET, [ChaosEvent("solo", 10.0, 20.0)])
    res = sim.run(LoadModel(kind="open", qps=qps), 10_000, KEY)
    assert float(res.utilization[0]) == pytest.approx(0.5, rel=1e-3)
    assert not bool(res.unstable[0])


def test_outage_truncation_shifts_offered_load():
    # entry: [call flaky (50%), call leaf]; flaky down => half the
    # requests transport-fail at step 0 and never reach leaf, so leaf's
    # offered load halves DURING the outage phase (VERDICT r2 weak #6:
    # static visits used to ignore where truncation redirects load)
    import numpy as np

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import ChaosEvent, SimParams
    from isotope_tpu.sim.engine import Simulator

    doc = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: flaky, probability: 50}
  - call: leaf
- name: flaky
- name: leaf
"""
    compiled = compile_graph(ServiceGraph.from_yaml(doc))
    chaos = (ChaosEvent(service="flaky", start_s=2.0, end_s=4.0),)
    sim = Simulator(compiled, SimParams(), chaos)
    names = list(compiled.services.names)
    visits = np.asarray(sim._visits_pc)  # (P, S); one combo
    starts = np.asarray(sim._phase_starts)
    outage = int(np.searchsorted(starts, 2.0, side="right") - 1)
    healthy = 0 if outage != 0 else 1
    e, f, le = (names.index(n) for n in ("entry", "flaky", "leaf"))
    # healthy phase: the static reach (flaky 0.5, leaf 1.0)
    assert visits[healthy, f] == pytest.approx(0.5)
    assert visits[healthy, le] == pytest.approx(1.0)
    # outage phase: flaky serves nothing; only the 50% of requests that
    # skipped the flaky call proceed to leaf
    assert visits[outage, f] == 0.0
    assert visits[outage, le] == pytest.approx(0.5)
    assert visits[outage, e] == pytest.approx(1.0)


def test_down_entry_phase_has_zero_visits():
    import numpy as np

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim.config import ChaosEvent, SimParams
    from isotope_tpu.sim.engine import Simulator

    doc = """
services:
- name: entry
  isEntrypoint: true
  script: [{call: leaf}]
- name: leaf
"""
    compiled = compile_graph(ServiceGraph.from_yaml(doc))
    chaos = (ChaosEvent(service="entry", start_s=1.0, end_s=2.0),)
    sim = Simulator(compiled, SimParams(), chaos)
    visits = np.asarray(sim._visits_pc)
    starts = np.asarray(sim._phase_starts)
    outage = int(np.searchsorted(starts, 1.0, side="right") - 1)
    assert (visits[outage] == 0.0).all()
