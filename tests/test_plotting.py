"""Plotter + example-topology + preset-config tests."""
import pathlib

import pytest

from isotope_tpu import cli
from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.plotting import plot_benchmark
from isotope_tpu.runner import load_toml

ROOT = pathlib.Path(__file__).parent.parent

CSV = """Labels,StartTime,RequestedQPS,ActualQPS,NumThreads,min,max,p50,p75,p90,p99,p999,errorPercent
canonical_none_1000qps_2c,t,1000,998,2,2500,4000,2800,2900,3000,3400,3800,0.0
canonical_none_1000qps_16c,t,1000,997,16,2500,4100,2850,2950,3100,3500,3900,0.0
canonical_istio_1000qps_2c,t,1000,998,2,4500,6000,4800,4900,5000,5400,5800,0.0
canonical_istio_1000qps_16c,t,1000,996,16,4500,6100,4850,4950,5100,5500,5900,0.0
"""


def test_plot_benchmark(tmp_path):
    csv = tmp_path / "benchmark.csv"
    csv.write_text(CSV)
    out = tmp_path / "plot.png"
    series = plot_benchmark(csv, out, x_axis="conn", metrics=["p50", "p99"])
    assert series == ["canonical_istio", "canonical_none"]
    assert out.stat().st_size > 1000  # a real PNG


def test_plot_unknown_metric(tmp_path):
    csv = tmp_path / "benchmark.csv"
    csv.write_text(CSV)
    with pytest.raises(ValueError, match="p12345"):
        plot_benchmark(csv, tmp_path / "x.png", metrics=["p12345"])


def test_plot_cli(tmp_path, capsys):
    csv = tmp_path / "benchmark.csv"
    csv.write_text(CSV)
    out = tmp_path / "o.png"
    rc = cli.main(["plot", str(csv), "--x", "conn", "-o", str(out)])
    assert rc == 0 and out.exists()


@pytest.mark.parametrize(
    "name",
    [p.name for p in sorted((ROOT / "examples/topologies").glob("*.yaml"))],
)
def test_example_topologies_compile(name):
    graph = ServiceGraph.from_yaml_file(ROOT / "examples/topologies" / name)
    compiled = compile_graph(
        graph, entry=None if graph.entrypoints() else graph.services[0].name
    )
    assert compiled.num_hops >= len(graph)


def test_preset_configs_load():
    for preset in ("latency.toml", "cpu_mem.toml"):
        cfg = load_toml(ROOT / "configs" / preset)
        assert cfg.topology_paths
        for path in cfg.topology_paths:
            assert pathlib.Path(path).exists(), path
        assert cfg.duration_s == 240.0


def test_fanout_examples_have_expected_scale():
    g = ServiceGraph.from_yaml_file(
        ROOT / "examples/topologies/10-svc_10000-end.yaml"
    )
    assert len(g) == 10
    assert sum(s.num_replicas for s in g.services) == 10_000
    g = ServiceGraph.from_yaml_file(
        ROOT / "examples/topologies/1000-svc_2000-end.yaml"
    )
    assert len(g) == 1000
    assert sum(s.num_replicas for s in g.services) == 2000


def test_series_label_exponent_qps():
    from isotope_tpu.plotting import _series_of

    # {:g} renders 1e6 qps as "1e+06" — the series split must still work
    assert _series_of("canonical_none_1e+06qps_8c") == "canonical_none"
    assert _series_of("canonical_none_maxqps_8c") == "canonical_none"
    assert _series_of("canonical_none_500qps_8c") == "canonical_none"


def test_plot_cpu_cores_from_sweep_csv(tmp_path):
    """End-to-end: sweep CSV carries cpu_cores_<svc> columns and the
    plotter can chart them (round-1 advisor finding (a))."""
    import json as _json

    from isotope_tpu.runner import load_toml, run_experiment

    topo = ROOT / "examples/topologies/canonical.yaml"
    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [500]
num_concurrent_connections = [2, 8]
duration = "120s"
load_kind = "open"

[sim]
num_requests = 1000
"""
    )
    out = tmp_path / "results"
    run_experiment(load_toml(cfg), out_dir=out)
    header = (out / "benchmark.csv").read_text().splitlines()[0]
    assert "cpu_cores_a" in header
    png = tmp_path / "cpu.png"
    series = plot_benchmark(
        out / "benchmark.csv", png, metrics=["cpu_cores_a"]
    )
    assert series and png.stat().st_size > 1000


def test_plot_tolerates_gap_cells(tmp_path):
    """Record-dependent columns are '-'-padded for rows from other
    topologies; the plotter must skip those rows, not crash."""
    csv = tmp_path / "benchmark.csv"
    csv.write_text(
        "Labels,StartTime,RequestedQPS,ActualQPS,NumThreads,p50,"
        "cpu_cores_a\n"
        "canonical_none_500qps_2c,t,500,499,2,2800,0.02\n"
        "other_none_500qps_2c,t,500,499,2,2600,-\n"
    )
    out = tmp_path / "p.png"
    series = plot_benchmark(csv, out, metrics=["cpu_cores_a"])
    assert series == ["canonical_none"]  # the '-'-only series is skipped
    assert out.stat().st_size > 1000
