"""DES-oracle tests: the fidelity axis of the north star.

Three layers (SURVEY.md §4's "validate distributions" strategy):

1. **Interpreter parity** — under deterministic service times and quiet
   load both the analytic engine and the DES oracle are exact, so their
   latencies must agree to float precision.  This pins the two
   *independent* implementations of the executable.go semantics
   (sleep/call/concurrent/probability/errorRate/retries/timeouts)
   against each other.
2. **Station physics** — the oracle's FIFO k-replica station must
   reproduce the M/M/1 closed forms it makes no direct use of.
3. **Fidelity** — the engine's p50/p99 must track the oracle's ground
   truth within 5% on chain, tree, and star at rho 0.3 and 0.7, open
   and closed loop (the north-star tolerance; BASELINE.json).  Known
   out-of-envelope regimes are documented in ORACLE.md.
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import ChaosEvent
from isotope_tpu.sim.oracle import OracleSimulator

KEY = jax.random.PRNGKey(3)
DET = SimParams(service_time="deterministic")
QUIET = LoadModel(kind="open", qps=0.001, duration_s=1.0)

CHAIN3 = """
services:
- name: a
  isEntrypoint: true
  script: [{call: b}]
- name: b
  script: [{call: c}]
- name: c
"""

TREE13 = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: c0}, {call: c1}, {call: c2}]
- name: c0
  script: [[{call: l00}, {call: l01}, {call: l02}]]
- name: c1
  script: [[{call: l10}, {call: l11}, {call: l12}]]
- name: c2
  script: [[{call: l20}, {call: l21}, {call: l22}]]
- name: l00
- name: l01
- name: l02
- name: l10
- name: l11
- name: l12
- name: l20
- name: l21
- name: l22
"""

STAR9 = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: s0}, {call: s1}, {call: s2}, {call: s3},
     {call: s4}, {call: s5}, {call: s6}, {call: s7}]
- name: s0
- name: s1
- name: s2
- name: s3
- name: s4
- name: s5
- name: s6
- name: s7
"""

MU = 1.0 / SimParams().cpu_time_s


def both(yaml_text, load, n_engine, n_oracle, params=SimParams(), seed=0):
    graph = ServiceGraph.from_yaml(yaml_text)
    engine = Simulator(compile_graph(graph), params)
    res_e = engine.run(load, n_engine, jax.random.fold_in(KEY, seed))
    oracle = OracleSimulator(graph, params)
    res_o = oracle.run(load, n_oracle, seed=seed)
    return res_e, res_o


# -- 1. interpreter parity (deterministic => exact agreement) -------------


def parity_case(yaml_text, **kwargs):
    res_e, res_o = both(yaml_text, QUIET, 32, 32, params=DET, **kwargs)
    lat_e = np.asarray(res_e.client_latency, np.float64)
    np.testing.assert_allclose(
        res_o.client_latency, lat_e, rtol=1e-5
    )
    np.testing.assert_array_equal(
        res_o.client_error, np.asarray(res_e.client_error)
    )
    assert res_o.hop_events == int(res_e.hop_events)


def test_parity_sequential_sleeps_and_calls():
    parity_case(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - sleep: 10ms
  - call: leaf
  - sleep: 5ms
- name: leaf
"""
    )


def test_parity_concurrent_join_with_sleep():
    parity_case(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{sleep: 30ms}, {call: fast}, {call: slow}]
- name: fast
- name: slow
  script: [{sleep: 50ms}]
"""
    )


def test_parity_error_rate_fast_500_skips_script():
    # errorRate 1.0 => child always 500s without running its script; a
    # downstream 500 does NOT fail the caller (executable.go:132-143)
    parity_case(
        """
services:
- name: entry
  isEntrypoint: true
  script: [{call: flaky}]
- name: flaky
  errorRate: 100%
  script: [{sleep: 80ms}]
"""
    )


def test_parity_retries_exhausted_by_500s():
    # 3 serial attempts, each a fast 500; final 500 still not transport
    parity_case(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: flaky, retries: 2}
- name: flaky
  errorRate: 100%
"""
    )


def test_parity_timeout_is_transport_and_truncates():
    # timeout < child sleep: attempt capped at the timeout, transport
    # error fails the caller at that step; the trailing sleep never runs
    parity_case(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: slow, timeout: 10ms}
  - sleep: 40ms
- name: slow
  script: [{sleep: 60ms}]
"""
    )


def test_parity_chaos_total_outage():
    graph = ServiceGraph.from_yaml(CHAIN3)
    chaos = (ChaosEvent(service="b", start_s=0.0, end_s=1e9),)
    engine = Simulator(compile_graph(graph), DET, chaos)
    res_e = engine.run(QUIET, 32, KEY)
    oracle = OracleSimulator(graph, DET, chaos)
    res_o = oracle.run(QUIET, 32, seed=0)
    assert res_o.client_error.all()
    assert np.asarray(res_e.client_error).all()
    np.testing.assert_allclose(
        res_o.client_latency,
        np.asarray(res_e.client_latency, np.float64),
        rtol=1e-5,
    )


def test_oracle_deterministic_per_seed():
    g = ServiceGraph.from_yaml(CHAIN3)
    o = OracleSimulator(g)
    a = o.run(LoadModel(kind="open", qps=5000.0), 2000, seed=42)
    b = o.run(LoadModel(kind="open", qps=5000.0), 2000, seed=42)
    c = o.run(LoadModel(kind="open", qps=5000.0), 2000, seed=43)
    np.testing.assert_array_equal(a.client_latency, b.client_latency)
    assert not np.array_equal(a.client_latency, c.client_latency)


# -- 2. station physics ----------------------------------------------------


def test_oracle_matches_mm1_closed_form():
    p = SimParams()
    sim = OracleSimulator(
        ServiceGraph.from_yaml("services:\n- name: a\n  isEntrypoint: true\n"),
        p,
    )
    lam = 0.7 * MU
    res = sim.run(LoadModel(kind="open", qps=lam), 1_000_000, seed=1)
    root_net = p.network.one_way(0) + p.network.one_way(0)
    soj = res.client_latency[res.client_start > 0.5] - root_net
    rate = MU - lam
    # M/M/1 FIFO sojourn ~ Exp(mu - lambda)
    assert np.quantile(soj, 0.5) == pytest.approx(np.log(2) / rate, rel=0.03)
    assert np.quantile(soj, 0.99) == pytest.approx(
        -np.log(0.01) / rate, rel=0.04
    )
    # measured utilization == offered rho
    dur = float(res.client_end.max())
    assert res.utilization(dur, sim.replicas)[0] == pytest.approx(
        0.7, rel=0.02
    )


# -- 3. fidelity: engine vs oracle ----------------------------------------


def fidelity_case(yaml_text, load, tol_p50, tol_p99, seed=0,
                  n_engine=200_000, n_oracle=1_000_000, warmup=0.5,
                  params=SimParams()):
    """``tol_*`` is a symmetric relative tolerance (float) or an
    asymmetric ``(lo, hi)`` band on the relative error ``e/o - 1`` —
    used where the engine sits on one documented side of the oracle,
    so drift in EITHER direction trips the gate."""
    res_e, res_o = both(yaml_text, load, n_engine, n_oracle,
                        params=params, seed=seed)
    lat_e = np.asarray(res_e.client_latency, np.float64)
    lat_o = res_o.client_latency[res_o.client_start >= warmup]
    for q, tol in ((0.5, tol_p50), (0.99, tol_p99)):
        e, o = np.quantile(lat_e, q), np.quantile(lat_o, q)
        lo, hi = tol if isinstance(tol, tuple) else (-tol, tol)
        rel = e / o - 1.0
        assert lo <= rel <= hi, (
            f"p{int(q * 100)}: engine={e * 1e3:.4f}ms "
            f"oracle={o * 1e3:.4f}ms err={rel * 100:+.2f}% "
            f"(band [{lo * 100:+.1f}%, {hi * 100:+.1f}%])"
        )
    return res_e, res_o


@pytest.mark.parametrize("rho", [0.3, 0.7])
@pytest.mark.parametrize(
    "name,yaml_text",
    [("chain3", CHAIN3), ("tree13", TREE13), ("star9", STAR9)],
)
def test_open_loop_fidelity(name, yaml_text, rho):
    load = LoadModel(kind="open", qps=rho * MU)
    fidelity_case(yaml_text, load, tol_p50=0.05, tol_p99=0.05)


@pytest.mark.parametrize(
    "name,yaml_text,rho,tol_p50,tol_p99",
    [
        # chains stay exact at high rho: each M/M/1 stage's departure
        # process is Poisson (Burke), so the per-station stationary law
        # composes without error (measured at <=1.4%)
        ("chain3", CHAIN3, 0.85, 0.03, 0.03),
        ("chain3", CHAIN3, 0.90, 0.03, 0.03),
        # fork-join trees drift as rho -> 1: subtree compositions are
        # hierarchically correlated — the depth-aware hierarchical
        # copula (SimParams.hierarchical_copula_gamma, r5) carries the
        # same-depth cousin correlation the flat copula missed,
        # tightening the r4 gates (0.85: 6%/4% -> 4%/4%; 0.9: 10%/5%
        # -> 5%/5%; measured +1.9%/+1.3% and +4.1%/+2.1% at gamma=0.9)
        ("tree13", TREE13, 0.85, 0.04, 0.04),
        ("tree13", TREE13, 0.90, 0.05, 0.05),
    ],
)
@pytest.mark.slow
@pytest.mark.slow
def test_open_loop_high_rho_envelope(name, yaml_text, rho, tol_p50, tol_p99):
    load = LoadModel(kind="open", qps=rho * MU)
    fidelity_case(
        yaml_text, load, tol_p50=tol_p50, tol_p99=tol_p99,
        n_engine=300_000, n_oracle=1_500_000, warmup=2.0,
    )


def test_closed_loop_paced_fidelity():
    # fortio's latency-benchmark mode: finite qps, many connections
    load = LoadModel(kind="closed", qps=0.5 * MU, connections=64)
    res_e, res_o = fidelity_case(
        CHAIN3, load, tol_p50=0.05, tol_p99=0.05,
        n_engine=128_000, n_oracle=512_000,
    )
    thr_o = len(res_o.client_latency) / float(res_o.client_end.max())
    assert float(res_e.offered_qps) == pytest.approx(thr_o, rel=0.02)


def test_closed_loop_saturated_throughput():
    # -qps max: the finite-population model's throughput (exact MVA on
    # chains) must match the oracle's measured throughput, and means
    # close through Little's law.
    load = LoadModel(kind="closed", qps=None, connections=64)
    res_e, res_o = both(CHAIN3, load, 128_000, 512_000)
    thr_o = len(res_o.client_latency) / float(res_o.client_end.max())
    assert float(res_e.offered_qps) == pytest.approx(thr_o, rel=0.02)
    lat_e = np.asarray(res_e.client_latency, np.float64)
    assert lat_e.mean() == pytest.approx(
        res_o.client_latency.mean(), rel=0.05
    )


@pytest.mark.parametrize(
    "name,yaml_text,tol_p50,tol_p99",
    [
        # chains are product-form: exact MVA + the variance-identity
        # population copula — tight envelope
        ("chain3", CHAIN3, 0.03, 0.05),
        # fork-join: finite-source decomposition closed by the r5
        # REGRESSION-SOLVED cycle fixed point (stable across RNG
        # streams; r4's damped iteration amplified pilot noise ~10x
        # and its tighter-looking quantiles were an irreproducible
        # basin accident) + partial population centering (alpha=0.25).
        # Measured r5 (seed-stable to 0.3%): tree13 p50 -7.7% /
        # p99 +0.7%; star9 p50 -20.8% / p99 -14.0% — star9's gap is a
        # near-uniform ~1 ms location shift from entry-leaf convoy
        # idleness the per-station census model cannot carry (ORACLE.md
        # "known out-of-envelope").  tree13's p99 tightens 10% -> 4%.
        ("tree13", TREE13, 0.09, 0.04),
        # star9 gates ASYMMETRICALLY (ADVICE r5): the engine is
        # uniformly FAST there, so the band pins the documented edge
        # from both sides — a tight +3% slow-side bound catches any
        # regression past the oracle, the fast side catches the known
        # convoy-idleness gap widening beyond its measured -20.8%/-14.0%
        # (the convoy-aware census fix is the ROADMAP follow-up).
        ("star9", STAR9, (-0.23, 0.03), (-0.16, 0.03)),
    ],
)
@pytest.mark.slow
@pytest.mark.slow
def test_closed_loop_saturated_fidelity(name, yaml_text, tol_p50, tol_p99):
    # The reference's CANONICAL experiment mode: qps="max", 64
    # connections (isotope/example-config.toml [client]); r3's +79% p99
    # regime, now modeled by the C-bounded population law.
    load = LoadModel(kind="closed", qps=None, connections=64)
    fidelity_case(
        yaml_text, load, tol_p50=tol_p50, tol_p99=tol_p99,
        n_engine=128_000, n_oracle=512_000,
    )


def test_closed_loop_saturated_probabilistic_chain():
    # visit ratios != 1 exercise the MVA cycle weighting (a reviewer-
    # caught double-count: cycle must sum cycle_visits * W alone) and
    # the sigma-weighted population copula (uniform equicorrelation
    # overestimated this p99 by +16%: station c is half-loaded, so the
    # a-b pair needs most of the negative correlation)
    yaml_text = """
services:
- name: a
  isEntrypoint: true
  script: [{call: b}]
- name: b
  script:
  - call: {service: c, probability: 50}
- name: c
"""
    load = LoadModel(kind="closed", qps=None, connections=64)
    res_e, res_o = fidelity_case(
        yaml_text, load, tol_p50=0.03, tol_p99=0.08,
        n_engine=128_000, n_oracle=512_000,
    )
    thr_o = len(res_o.client_latency) / float(res_o.client_end.max())
    assert float(res_e.offered_qps) == pytest.approx(thr_o, rel=0.02)


def test_closed_loop_saturated_mixed_replicas():
    # a single-replica bottleneck between multi-replica stations: the
    # census mixture sits at high Erlang stages, where the old W(0)=0
    # polynomial anchor undersampled the whole low-quantile region
    # (sampled mean 3.46ms vs the Little-law 4.92ms)
    yaml_text = """
services:
- name: a
  isEntrypoint: true
  numReplicas: 2
  script: [{call: b}]
- name: b
  numReplicas: 1
  script: [{call: c}]
- name: c
  numReplicas: 2
"""
    load = LoadModel(kind="closed", qps=None, connections=64)
    res_e, res_o = fidelity_case(
        yaml_text, load, tol_p50=0.03, tol_p99=0.04,
        n_engine=64_000, n_oracle=256_000,
    )
    thr_o = len(res_o.client_latency) / float(res_o.client_end.max())
    assert float(res_e.offered_qps) == pytest.approx(thr_o, rel=0.02)


def test_closed_loop_saturated_under_chaos_phases():
    # ORACLE.md's (former) out-of-envelope #3: phased -qps max runs.
    # Per-phase MVA tables + the piecewise nominal time warp track the
    # oracle inside AND outside the chaos window (measured: pre
    # +1.2/+1.6%, chaos -0.5/+1.3%, post +0.1/+1.8%).
    yaml_text = """
services:
- name: a
  isEntrypoint: true
  numReplicas: 2
  script: [{call: b}]
- name: b
  numReplicas: 2
  script: [{call: c}]
- name: c
  numReplicas: 2
"""
    g = ServiceGraph.from_yaml(yaml_text)
    load = LoadModel(kind="closed", qps=None, connections=64)
    chaos = (ChaosEvent(service="b", start_s=1.0, end_s=3.0,
                        replicas_down=1),)
    engine = Simulator(compile_graph(g), SimParams(), chaos)
    res = engine.run(load, 128_000, jax.random.fold_in(KEY, 9))
    st = np.asarray(res.client_start)
    lat = np.asarray(res.client_latency, np.float64)
    oracle = OracleSimulator(g, SimParams(), chaos)
    ro = oracle.run(load, 256_000, seed=0)
    for lo, hi, name in ((0.2, 1.0, "pre"), (1.15, 3.0, "chaos"),
                         (3.3, 1e9, "post")):
        m_e = (st >= lo) & (st <= hi)
        m_o = (ro.client_start >= lo) & (ro.client_start <= hi)
        for q, tol in ((0.5, 0.03), (0.99, 0.05)):
            e = np.quantile(lat[m_e], q)
            o = np.quantile(ro.client_latency[m_o], q)
            assert e == pytest.approx(o, rel=tol), (
                f"{name} p{int(q * 100)}: engine={e * 1e3:.3f}ms "
                f"oracle={o * 1e3:.3f}ms err={(e / o - 1) * 100:+.2f}%"
            )


@pytest.mark.parametrize(
    "service_time,param,tol_p50,tol_p99",
    [
        # heavy-tail saturated closed loop: the census-conditional wait
        # uses SCV-matched gamma stages and the census itself is
        # QNA-compressed (sim/closed.py) — measured lognormal
        # -1.7%/-4.7%, pareto +3.1%/-4.8%
        ("lognormal", 1.0, 0.05, 0.08),
        ("pareto", 2.5, 0.06, 0.08),
        # deterministic saturated closed loop (the reference's scripts
        # are FIXED sleeps, executable.go:78-82, so this is the
        # canonical -qps max regime): the scv<1 census factor
        # sqrt(scv), the pipeline-bound throughput blend, and the
        # Little-law table rescale (sim/closed.py) bring the formerly
        # ungated +4%/+25% (VERDICT r4) to measured -0.0%/+2.0%;
        # throughput is within 0.1% of the capacity bound
        ("deterministic", 1.0, 0.03, 0.05),
    ],
)
def test_closed_loop_saturated_heavy_tails(service_time, param, tol_p50,
                                           tol_p99):
    load = LoadModel(kind="closed", qps=None, connections=64)
    params = SimParams(service_time=service_time,
                       service_time_param=param)
    fidelity_case(
        CHAIN3, load, tol_p50=tol_p50, tol_p99=tol_p99,
        n_engine=64_000, n_oracle=256_000, seed=0, params=params,
    )


@pytest.mark.slow
@pytest.mark.slow
def test_closed_loop_saturated_fork_join_throughput():
    # fork-join saturated throughput: self-consistent fixed point lands
    # within 8% of the oracle (r4 measured: tree13 +6.3%, star9 +5.2%).
    # ASYMMETRIC band (the star9 p50/p99 discipline, ADVICE r5): the
    # engine is uniformly FAST here — star9's convoy idleness slows the
    # oracle, not the engine — so the slow side pins tight at -3% to
    # catch any regression below the oracle while the fast side guards
    # the documented +5-6% edge from widening past +8%.
    load = LoadModel(kind="closed", qps=None, connections=64)
    for yaml_text in (TREE13, STAR9):
        res_e, res_o = both(yaml_text, load, 64_000, 256_000)
        thr_o = len(res_o.client_latency) / float(res_o.client_end.max())
        rel = float(res_e.offered_qps) / thr_o - 1.0
        assert -0.03 <= rel <= 0.08, (
            f"saturated throughput: engine={float(res_e.offered_qps):.1f} "
            f"oracle={thr_o:.1f} err={rel * 100:+.2f}% outside "
            f"[-3%, +8%]"
        )


RETRY_STORM = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
"""


def test_retry_storm_feedback_matches_oracle_collapse():
    # VERDICT r3 §2: chaos-phase retry amplification must feed back into
    # utilization.  Killing 2/4 worker replicas pushes waits past the
    # 850us call timeout; timed-out work stays queued while retries pile
    # on — the DES falls into the storm branch where every attempt times
    # out.  The static tables see rho=0.65 ("healthy"); the feedback
    # fixed point (sim/feedback.py) finds the storm branch, flags the
    # phase unstable, and the timeout-bounded latencies then match the
    # oracle tightly (measured in-window err: p50 +0.003%, p99 +0.09%).
    qps = 0.325 * 4 * MU
    load = LoadModel(kind="open", qps=qps)
    chaos = (ChaosEvent(service="worker", start_s=2.0, end_s=15.0,
                        replicas_down=2),)
    graph = ServiceGraph.from_yaml(RETRY_STORM)

    engine = Simulator(compile_graph(graph), SimParams(), chaos)
    assert engine._feedback is not None
    res = engine.run(load, 400_000, KEY)
    st = np.asarray(res.client_start)
    lat = np.asarray(res.client_latency, np.float64)

    oracle = OracleSimulator(graph, SimParams(), chaos)
    ro = oracle.run(load, 600_000, seed=0)

    # pre-chaos, in-chaos, AND post-chaos: the drain-window model keeps
    # the storm row live for backlog/freed-capacity seconds after the
    # chaos ends (~9 s here), so the post window tracks the oracle's
    # drain transient too (measured -44.6% -> -0.04% without/with)
    for lo, hi, tol in ((0.5, 2.0, 0.03), (2.2, 15.0, 0.03),
                        (16.0, 23.0, 0.04)):
        m_e = (st >= lo) & (st <= hi)
        m_o = (ro.client_start >= lo) & (ro.client_start <= hi)
        for q in (0.5, 0.99):
            e = np.quantile(lat[m_e], q)
            o = np.quantile(ro.client_latency[m_o], q)
            assert e == pytest.approx(o, rel=tol), (
                f"[{lo},{hi}] p{int(q * 100)}: engine={e * 1e3:.3f}ms "
                f"oracle={o * 1e3:.3f}ms err={(e / o - 1) * 100:+.1f}%"
            )
    # the storm phase is detected: utilization >= 1 on the worker
    assert bool(np.asarray(res.unstable)[1])

    # the static tables are blind to the storm: without feedback the
    # chaos-window median is off by tens of percent and nothing is
    # flagged — this is exactly the gap the fixed point closes
    blind = Simulator(compile_graph(graph), SimParams(), chaos)
    blind._feedback = None
    res_b = blind.run(load, 400_000, KEY)
    st_b = np.asarray(res_b.client_start)
    lat_b = np.asarray(res_b.client_latency, np.float64)
    m_b = (st_b >= 2.2) & (st_b <= 15.0)
    m_o = (ro.client_start >= 2.2) & (ro.client_start <= 15.0)
    p50_b = np.quantile(lat_b[m_b], 0.5)
    p50_o = np.quantile(ro.client_latency[m_o], 0.5)
    assert p50_b < 0.6 * p50_o
    assert not bool(np.asarray(res_b.unstable).any())


def test_retry_feedback_inactive_without_timeouts():
    # no finite timeout => failure probabilities are static; the solver
    # must not even be constructed (zero overhead on the common path)
    graph = ServiceGraph.from_yaml(CHAIN3)
    assert Simulator(compile_graph(graph))._feedback is None


def test_retry_feedback_quiet_load_matches_static():
    # with generous timeouts at low load the fixed point must reproduce
    # the static visit tables (the feedback is a correction, not a bias)
    graph = ServiceGraph.from_yaml(RETRY_STORM)
    engine = Simulator(compile_graph(graph))
    dyn = engine._feedback.visits_pc(0.01 * MU)
    static = np.asarray(engine._visits_pc, np.float64)
    np.testing.assert_allclose(dyn, static, rtol=0.02)


def test_retry_feedback_respects_error_rate_reach():
    # the dynamic reach must carry the (1 - parent_err) 500-skip factor
    # static hop_reach has: a 20% entry errorRate means only 80% of
    # requests reach the worker (and target_err discounts retries)
    yaml_text = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 20%
  script:
  - call: {service: worker, timeout: 10s, retries: 2}
- name: worker
  errorRate: 10%
"""
    graph = ServiceGraph.from_yaml(yaml_text)
    engine = Simulator(compile_graph(graph))
    dyn = engine._feedback.visits_pc(0.01 * MU)
    static = np.asarray(engine._visits_pc, np.float64)
    # worker static visits = 0.8 * (1 + 0.1 + 0.01) = 0.888
    assert static[0, 1] == pytest.approx(0.888, rel=1e-6)
    np.testing.assert_allclose(dyn, static, rtol=0.02)


def test_error_rate_fidelity():
    # client-visible error fraction: entry 500s with its own rate;
    # downstream 500s do not propagate
    yaml_text = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 10%
  script: [{call: leaf}]
- name: leaf
  errorRate: 50%
"""
    load = LoadModel(kind="open", qps=0.3 * MU)
    res_e, res_o = both(yaml_text, load, 100_000, 200_000)
    frac_e = float(np.asarray(res_e.client_error).mean())
    frac_o = float(res_o.client_error.mean())
    assert frac_e == pytest.approx(0.10, abs=0.01)
    assert frac_o == pytest.approx(0.10, abs=0.01)


def test_call_probability_fidelity():
    yaml_text = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: maybe, probability: 50}
- name: maybe
  script: [{sleep: 20ms}]
"""
    res_e, res_o = both(yaml_text, QUIET, 4000, 4000, params=DET)
    # ~half the requests pay the 20ms call
    long_e = (np.asarray(res_e.client_latency) > 0.02).mean()
    long_o = (res_o.client_latency > 0.02).mean()
    assert long_e == pytest.approx(0.5, abs=0.03)
    assert long_o == pytest.approx(0.5, abs=0.03)
