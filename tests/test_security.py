"""Security policy generator (generate_policies parity)."""
import base64
import json

import pytest
import yaml

# the generator signs real JWKS material; without the optional
# cryptography wheel these tests cannot run (don't fail a CPU-only
# image over a missing native dep — gate, per the repo's no-new-deps
# policy)
pytest.importorskip("cryptography")

from isotope_tpu import cli
from isotope_tpu.convert.security import (
    AuthZ,
    RequestAuthN,
    SecurityPolicyConfig,
    generate_policies,
)

CONFIG_JSON = """
{
  "authZ": {
    "action": "ALLOW",
    "numPolicies": 2,
    "numPaths": 3,
    "numSourceIP": 1,
    "numValues": 2,
    "numRequestPrincipals": 2
  },
  "namespace": "twopods-istio",
  "peerAuthN": {"mtlsMode": "STRICT", "numPolicies": 1},
  "requestAuthN": {"numPolicies": 1, "numJwks": 2}
}
"""


def test_config_schema_round_trip():
    cfg = SecurityPolicyConfig.from_json(CONFIG_JSON)
    assert cfg.authz.action == "ALLOW"
    assert cfg.authz.num_policies == 2
    assert cfg.authz.num_paths == 3
    assert cfg.peer_authn.mtls_mode == "STRICT"
    assert cfg.request_authn.num_jwks == 2


def test_generated_manifests_shapes():
    cfg = SecurityPolicyConfig.from_json(CONFIG_JSON)
    text, token = generate_policies(cfg)
    docs = list(yaml.safe_load_all(text))
    kinds = [d["kind"] for d in docs]
    assert kinds.count("AuthorizationPolicy") == 2
    assert kinds.count("PeerAuthentication") == 1
    assert kinds.count("RequestAuthentication") == 1

    authz = docs[0]
    (rule,) = authz["spec"]["rules"]
    assert authz["spec"]["action"] == "ALLOW"
    # generate.go's synthetic values, verbatim
    (to,) = rule["to"]
    assert to["operation"]["paths"] == [
        "/invalid-path-0", "/invalid-path-1", "/invalid-path-2"
    ]
    ips = rule["from"][0]["source"]["ipBlocks"]
    assert ips == ["0.0.0.0"]
    # only the LAST request principal is valid (generate.go:119-126)
    rp = rule["from"][1]["source"]["requestPrincipals"]
    assert rp == ["invalid-issuer/subject", "issuer-2/subject"]
    # ALLOW puts "admin" last in the condition values (generate.go:55-70)
    (when,) = rule["when"]
    assert when["key"] == "request.headers[x-token]"
    assert when["values"] == ["guest", "admin"]

    ra = docs[-1]
    rules = ra["spec"]["jwtRules"]
    assert [r["issuer"] for r in rules] == ["issuer-1", "issuer-2"]
    assert token is not None


def test_token_verifies_against_jwks():
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import (
        padding,
        rsa,
    )

    cfg = SecurityPolicyConfig(
        request_authn=RequestAuthN(num_policies=1, num_jwks=1)
    )
    text, token = generate_policies(cfg)
    (doc,) = list(yaml.safe_load_all(text))
    jwks = json.loads(doc["spec"]["jwtRules"][0]["jwks"])
    (jwk,) = jwks["keys"]

    def unb64(s):
        return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

    n = int.from_bytes(unb64(jwk["n"]), "big")
    e = int.from_bytes(unb64(jwk["e"]), "big")
    pub = rsa.RSAPublicNumbers(e, n).public_key()

    header, payload, sig = token.split(".")
    pub.verify(  # raises on mismatch
        unb64(sig), f"{header}.{payload}".encode(),
        padding.PKCS1v15(), hashes.SHA256(),
    )
    claims = json.loads(unb64(payload))
    assert claims == {"iss": "issuer-1", "sub": "subject"}


def test_invalid_token_does_not_verify():
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    from cryptography.exceptions import InvalidSignature

    cfg = SecurityPolicyConfig(
        request_authn=RequestAuthN(
            num_policies=1, num_jwks=1, invalid_token=True
        )
    )
    text, token = generate_policies(cfg)
    (doc,) = list(yaml.safe_load_all(text))
    jwk = json.loads(doc["spec"]["jwtRules"][0]["jwks"])["keys"][0]

    def unb64(s):
        return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

    pub = rsa.RSAPublicNumbers(
        int.from_bytes(unb64(jwk["e"]), "big"),
        int.from_bytes(unb64(jwk["n"]), "big"),
    ).public_key()
    header, payload, sig = token.split(".")
    with pytest.raises(InvalidSignature):
        pub.verify(
            unb64(sig), f"{header}.{payload}".encode(),
            padding.PKCS1v15(), hashes.SHA256(),
        )


def test_dry_run_annotation():
    cfg = SecurityPolicyConfig(authz=AuthZ(num_policies=1, dry_run=True))
    text, _ = generate_policies(cfg)
    (doc,) = list(yaml.safe_load_all(text))
    assert doc["metadata"]["annotations"] == {"istio.io/dry-run": "true"}


def test_cli_security_policies(tmp_path, capsys):
    cfg = tmp_path / "c.json"
    cfg.write_text(CONFIG_JSON)
    out = tmp_path / "policies.yaml"
    tok = tmp_path / "token.txt"
    rc = cli.main(
        ["security-policies", str(cfg), "-o", str(out),
         "--token-out", str(tok)]
    )
    assert rc == 0
    assert len(list(yaml.safe_load_all(out.read_text()))) == 4
    assert tok.read_text().count(".") == 2


def test_token_issuer_matches_rules_when_numjwks_zero():
    # numJwks omitted: jwtRules carry issuer-1, the token must too
    cfg = SecurityPolicyConfig(
        authz=AuthZ(num_policies=1, num_request_principals=2),
        request_authn=RequestAuthN(num_policies=1),
    )
    text, token = generate_policies(cfg)
    docs = list(yaml.safe_load_all(text))
    rules = docs[-1]["spec"]["jwtRules"]
    assert [r["issuer"] for r in rules] == ["issuer-1"]
    payload = token.split(".")[1]
    claims = json.loads(
        base64.urlsafe_b64decode(payload + "=" * (-len(payload) % 4))
    )
    assert claims["iss"] == "issuer-1"
    rp = docs[0]["spec"]["rules"][0]["from"][0]["source"][
        "requestPrincipals"
    ]
    assert rp[-1] == "issuer-1/subject"


def test_jwks_base64url_is_unpadded():
    cfg = SecurityPolicyConfig(
        request_authn=RequestAuthN(num_policies=1, num_jwks=1)
    )
    text, _ = generate_policies(cfg)
    (doc,) = list(yaml.safe_load_all(text))
    jwk = json.loads(doc["spec"]["jwtRules"][0]["jwks"])["keys"][0]
    assert "=" not in jwk["n"] and "=" not in jwk["e"]
