"""Critical-path blame attribution (metrics/attribution.py).

Invariants pinned here:

- per-request blame sums to client latency within f32 accumulation
  noise (the ``residual`` evidence);
- scan-blocked accumulation equals single-block accumulation;
- the sharded psum merge equals the single-device host merge;
- ``SimParams.attribution=False`` leaves every RunSummary field
  byte-identical (and an attributed run's RunSummary matches the
  unattributed run of the same arguments bit-for-bit);
- every summary leaf stays O(H) / O(S * buckets) / O(K * H) — never
  O(N * H);
- semantic blame: chains put every hop on the critical path, forks
  blame the slow branch, timeouts charge the edge, errorRate 500s are
  counted.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics import attribution
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import LoadModel, MtlsSchedule, SimParams
from isotope_tpu.sim.engine import Simulator

KEY = jax.random.PRNGKey(0)
LOAD = LoadModel(kind="open", qps=200.0)


def _graph(doc: dict) -> ServiceGraph:
    doc.setdefault("defaults", {"requestSize": 64, "responseSize": 64})
    return ServiceGraph.decode(doc)


@pytest.fixture(scope="module")
def tree13():
    return compile_graph(
        ServiceGraph.from_yaml_file(
            "examples/topologies/tree-13-services.yaml"
        )
    )


@pytest.fixture(scope="module")
def attr_sim(tree13):
    return Simulator(tree13, SimParams(attribution=True))


def _run(sim, n=1024, block=256, **kw):
    return sim.run_attributed(LOAD, n, KEY, block_size=block, **kw)


# -- exactness ---------------------------------------------------------------


def test_blame_sums_to_client_latency(attr_sim):
    s, a = _run(attr_sim)
    count = float(a.count)
    assert count == float(s.count)
    # per-request residual at f32 noise level (sub-microsecond on
    # millisecond latencies)
    assert float(a.residual_abs) / count < 1e-6
    # total attributed time reproduces the accumulated latency sum
    np.testing.assert_allclose(
        a.total_blame_s, float(s.latency_sum), rtol=1e-5
    )


def test_self_blame_nonnegative(attr_sim):
    _, a = _run(attr_sim)
    assert float(np.asarray(a.self_blame).min()) > -1e-7
    assert float(np.asarray(a.wait_blame).min()) >= 0.0


def test_hist_counts_match_crit_counts(attr_sim):
    _, a = _run(attr_sim)
    np.testing.assert_allclose(
        float(np.asarray(a.hist).sum()),
        float(np.asarray(a.crit_count).sum()),
        rtol=1e-6,
    )


# -- scan-block equivalence --------------------------------------------------


def _split_results(res, cut):
    """Slice a SimResults' per-request leaves into [:cut] / [cut:]."""
    def part(sl):
        return res._replace(
            client_start=res.client_start[sl],
            client_latency=res.client_latency[sl],
            client_error=res.client_error[sl],
            hop_sent=res.hop_sent[sl],
            hop_error=res.hop_error[sl],
            hop_latency=res.hop_latency[sl],
            hop_start=res.hop_start[sl],
            hop_wait=res.hop_wait[sl],
        )

    return part(slice(None, cut)), part(slice(cut, None))


@pytest.mark.slow
@pytest.mark.slow
def test_blocked_accumulation_equals_single_block(attr_sim):
    res = attr_sim.run(LOAD, 512, KEY)
    tables = attr_sim._attribution_tables()
    full, _ = attribution.attribute_block(res, tables)
    lo, hi = _split_results(res, 256)
    a1, _ = attribution.attribute_block(lo, tables)
    a2, _ = attribution.attribute_block(hi, tables)
    summed = jax.tree.map(
        lambda x, y: x + y,
        a1._replace(tail_cut=jnp.float32(0.0)),
        a2._replace(tail_cut=jnp.float32(0.0)),
    )
    for name, got, want in zip(
        full._fields, summed, full._replace(tail_cut=jnp.float32(0.0))
    ):
        if got is None:
            assert want is None, name
            continue
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=1e-7,
            err_msg=name,
        )


# -- gating / byte-identity --------------------------------------------------


def test_off_leaves_run_summary_byte_identical(tree13, attr_sim):
    plain = Simulator(tree13)  # attribution defaults off
    s_off = plain.run_summary(LOAD, 1024, KEY, block_size=256)
    s_on, _ = _run(attr_sim)
    for name, a, b in zip(
        s_off._fields,
        s_off._replace(metrics=None),
        s_on._replace(metrics=None),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_run_attributed_requires_flag(tree13):
    sim = Simulator(tree13)
    with pytest.raises(ValueError, match="attribution=True"):
        sim.run_attributed(LOAD, 64, KEY)


def test_attribution_rejects_mtls(tree13):
    with pytest.raises(ValueError, match="MtlsSchedule"):
        Simulator(
            tree13, SimParams(attribution=True),
            mtls=MtlsSchedule(period_s=1.0, taxes_s=(0.0, 1e-3)),
        )


def test_summary_stays_o_buckets(attr_sim, tree13):
    # no leaf may scale with the request count: with N=4096 requests
    # every array is bounded by S * blame buckets (hist) or K * H
    # (exemplars)
    n = 4096
    _, a = _run(attr_sim, n=n, block=512)
    bound = max(
        tree13.num_services * attribution.NUM_BLAME_BUCKETS,
        attr_sim.params.attribution_top_k * tree13.num_hops,
    )
    for leaf in jax.tree.leaves(a):
        assert np.asarray(leaf).size <= bound
        assert np.asarray(leaf).size < n


# -- tail mode / exemplars ---------------------------------------------------


def test_tail_restricts_and_exemplars_are_slowest(attr_sim):
    s, a = _run(attr_sim, n=2048, block=512, tail=True)
    assert np.isfinite(float(a.tail_cut))
    assert 0 < float(a.tail_count) < float(a.count)
    # tail accumulators are a sub-population of the mean ones
    assert a.tail_total_blame_s < a.total_blame_s
    assert float(np.asarray(a.tail_hist).sum()) <= float(
        np.asarray(a.hist).sum()
    )
    ex = a.exemplars
    lat = np.asarray(ex.latency)
    assert list(lat) == sorted(lat, reverse=True)
    # identical streams to the RunSummary: the slowest exemplar IS the
    # run's max latency
    np.testing.assert_allclose(lat[0], float(s.latency_max), rtol=0)


def test_exemplar_trace_shapes(attr_sim, tree13):
    import json

    from isotope_tpu.metrics.trace import write_trace

    _, a = _run(attr_sim, n=512, block=256, tail=True)
    out = {}
    for fmt in ("jaeger", "chrome"):
        path = f"/tmp/isotope_test_exemplars.{fmt}.json"
        count = write_trace(path, tree13, fmt=fmt, exemplars=a)
        assert count == attr_sim.params.attribution_top_k
        out[fmt] = json.load(open(path))
    tr = out["jaeger"]["data"][0]
    tags = {t["key"]: t["value"] for t in tr["spans"][0]["tags"]}
    assert tags["tail_rank"] == 0
    assert tags["tail_cut_s"] == pytest.approx(float(a.tail_cut))
    ev = out["chrome"]["traceEvents"][0]
    assert ev["args"]["tail_rank"] == 0


# -- sharded psum merge ------------------------------------------------------


@pytest.mark.slow
@pytest.mark.slow
def test_sharded_psum_equals_single_device(tree13):
    from isotope_tpu.parallel import ShardedSimulator, make_mesh

    sh = ShardedSimulator(
        tree13, make_mesh(4, 2), SimParams(attribution=True)
    )
    s1, a1 = sh.run_attributed(LOAD, 4096, KEY, block_size=512,
                               tail=True)
    s2, a2 = sh.run_attributed_emulated(
        LOAD, 4096, KEY, block_size=512, tail=True,
        tail_cut=float(a1.tail_cut),
    )
    for name, x, y in zip(
        a1._fields,
        a1._replace(exemplars=None),
        a2._replace(exemplars=None),
    ):
        if x is None:
            continue
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-6,
            err_msg=name,
        )
    np.testing.assert_allclose(
        np.asarray(a1.exemplars.latency),
        np.asarray(a2.exemplars.latency),
        rtol=0,
    )
    # residual invariant survives the mesh
    assert float(a1.residual_abs) / float(a1.count) < 1e-6


# -- semantic blame ----------------------------------------------------------


def _attr_for(doc: dict, qps=50.0, n=256, **params):
    compiled = compile_graph(_graph(doc))
    sim = Simulator(compiled, SimParams(attribution=True, **params))
    load = LoadModel(kind="open", qps=qps)
    s, a = sim.run_attributed(load, n, KEY, block_size=n)
    return compiled, s, a


def test_chain_puts_every_hop_on_the_path():
    doc = {
        "services": [
            {"name": "a", "isEntrypoint": True,
             "script": [{"call": "b"}]},
            {"name": "b", "script": [{"call": "c"}]},
            {"name": "c", "script": [{"sleep": "2ms"}]},
        ]
    }
    compiled, s, a = _attr_for(doc)
    crit = np.asarray(a.crit_count)
    assert np.all(crit == float(a.count))
    # c's self blame carries its deterministic sleep
    self_per_req = np.asarray(a.self_blame) / float(a.count)
    assert self_per_req[2] > 2e-3


def test_fork_blames_the_slow_branch():
    doc = {
        "services": [
            {"name": "entry", "isEntrypoint": True,
             # one concurrent group: slow and fast fan out together
             "script": [[{"call": "slow"}, {"call": "fast"}]]},
            {"name": "slow", "script": [{"sleep": "20ms"}]},
            {"name": "fast", "script": [{"sleep": "10us"}]},
        ]
    }
    compiled, s, a = _attr_for(doc)
    names = compiled.services.names
    crit = {
        names[compiled.hop_service[h]]: c
        for h, c in enumerate(np.asarray(a.crit_count))
    }
    count = float(a.count)
    assert crit["entry"] == count
    assert crit["slow"] / count > 0.99
    assert crit["fast"] / count < 0.01
    rows = {r["service"]: r for r in attribution.service_blame(
        compiled, a)}
    assert rows["slow"]["share"] > rows.get(
        "fast", {"share": 0.0}
    )["share"]
    # the 20ms sleep dominates the slow branch's self blame
    assert rows["slow"]["self_s"] / count > 15e-3


def test_timeout_charges_the_edge():
    doc = {
        "services": [
            {"name": "a", "isEntrypoint": True,
             "script": [
                 {"call": {"service": "b", "timeout": "1ms"}}
             ]},
            {"name": "b", "script": [{"sleep": "50ms"}]},
        ]
    }
    compiled, s, a = _attr_for(doc)
    tmo = np.asarray(a.timeout_blame)
    # hop 1 (the call into b) carries ~1ms of timeout blame per request
    assert tmo[1] / float(a.count) == pytest.approx(1e-3, rel=1e-3)
    # b's subtree is off the caller's clock: no self blame recursed
    assert float(np.asarray(a.self_blame)[1]) == 0.0
    # the sum invariant survives truncation
    assert float(a.residual_abs) / float(a.count) < 1e-6
    edges = attribution.edge_blame(compiled, a)
    ab = [e for e in edges if e["callee"] == "b"][0]
    assert ab["timeout_s"] > 0


def test_error_contributions_counted():
    doc = {
        "services": [
            {"name": "a", "isEntrypoint": True,
             "script": [{"call": "b"}]},
            {"name": "b", "errorRate": "50%",
             "script": [{"sleep": "1ms"}]},
        ]
    }
    compiled, s, a = _attr_for(doc, n=512)
    errs = np.asarray(a.error_count)
    assert errs[1] > 0  # b 500s about half the time
    assert float(a.residual_abs) / float(a.count) < 1e-6


# -- shared detail-mode plumbing (commands/common.py) ------------------------


def test_detail_mode_composes(monkeypatch):
    from isotope_tpu import telemetry
    from isotope_tpu.commands.common import arm_telemetry

    telemetry.disable()
    try:
        assert arm_telemetry("detail") is True
        # a later plain --telemetry must NOT strip the armed fences
        assert arm_telemetry("on") is True
        telemetry.disable()
        assert arm_telemetry("on") is False
        # and an independent --detail request composes on top
        assert arm_telemetry("on", detail=True) is True
    finally:
        telemetry.disable()


def test_vet_memory_ratio_gauge():
    # ROADMAP follow-up groundwork: the measured/estimated peak-bytes
    # ratio gauge that will calibrate CAPACITY_FILL from real runs
    from isotope_tpu import telemetry
    from isotope_tpu.runner.run import _record_vet_memory_ratio

    telemetry.reset()
    _record_vet_memory_ratio()  # neither gauge present: no-op
    assert telemetry.gauge_get("vet_peak_bytes_measured_ratio") is None
    telemetry.gauge_set("vet_peak_bytes_estimate", 200.0)
    _record_vet_memory_ratio()  # estimate alone: still no ratio
    assert telemetry.gauge_get("vet_peak_bytes_measured_ratio") is None
    telemetry.gauge_set("device_memory_peak_bytes_max", 170.0)
    _record_vet_memory_ratio()
    assert telemetry.gauge_get(
        "vet_peak_bytes_measured_ratio"
    ) == pytest.approx(0.85)
    telemetry.reset()
