"""Bucketed level-scan executor: planning + equivalence vs the unroll.

Equivalence contract (sim/levelscan.py): the scan body performs the
same operations in the same order as the unrolled path, so

- executed EAGERLY (op-by-op rounding) the two executors are
  **bit-for-bit identical** on every SimResults field, and
- under jit, every discrete field (sent/error masks, counters) is
  still exactly equal while float fields may differ by at most ~1 f32
  ULP — XLA is free to fuse multiply-add chains differently across the
  two program shapes (CPU LLVM emits FMAs per fusion boundary).

Covered graph shapes (ISSUE 1): the tree121 flagship, a skewed
multitier topology, and a retry+timeout+errorRate graph; plus a
sparse-island mix and the summary scan path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.compiler.buckets import (
    LevelShape,
    ScanBucketPlan,
    UnrolledLevelPlan,
    plan_segments,
)
from isotope_tpu.models.generators import realistic_topology, tree_topology
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import OPEN_LOOP, ChaosEvent
from isotope_tpu.sim.levelscan import ScanBucket

KEY = jax.random.PRNGKey(11)
OPEN = LoadModel(kind="open", qps=500.0)

# a high waste budget forces every eligible level into buckets so the
# scan path is exercised even on geometric trees
SCAN = dict(level_bucket_waste=64.0)
UNROLLED = dict(bucketed_scan=False)

RETRY_TIMEOUT_YAML = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 2%
  script:
  - call: {service: mid, timeout: 30ms, retries: 2}
  - sleep: 1ms
- name: mid
  errorRate: 5%
  script:
  - - call: {service: leaf, timeout: 10ms, retries: 1}
    - call: {service: leaf2, probability: 60}
- name: leaf
  errorRate: 3%
- name: leaf2
  script:
  - call: deep
- name: deep
"""


def _tree121():
    return compile_graph(
        ServiceGraph.decode(
            tree_topology(num_levels=5, num_branches=3,
                          request_size=1024, response_size=1024)
        )
    )


def _multitier():
    """Skewed multitier DAG — uneven level widths, long scripts."""
    return compile_graph(
        ServiceGraph.decode(
            realistic_topology(60, archetype="multitier", seed=1)
        )
    )


def _retry_graph():
    return compile_graph(ServiceGraph.from_yaml(RETRY_TIMEOUT_YAML))


def _num_scan(sim):
    return sum(1 for s in sim._segments if isinstance(s, ScanBucket))


def _assert_equivalent(compiled, load=OPEN, n=256, params=(), chaos=(),
                       key=KEY):
    base = dict(params)
    sim_scan = Simulator(compiled, SimParams(**{**base, **SCAN}), chaos)
    sim_unrl = Simulator(compiled, SimParams(**{**base, **UNROLLED}),
                         chaos)
    assert _num_scan(sim_scan) >= 1, "scan path did not engage"
    assert _num_scan(sim_unrl) == 0

    # -- eager: op-by-op identical => bit-for-bit --------------------------
    args = (key, jnp.float32(load.qps or 500.0), jnp.float32(0.0),
            jnp.float32(load.qps or 500.0))
    if load.kind == OPEN_LOOP:
        r_eager_s = sim_scan._simulate(n, OPEN_LOOP, 0, False, *args)
        r_eager_u = sim_unrl._simulate(n, OPEN_LOOP, 0, False, *args)
        for f in r_eager_s._fields:
            a = getattr(r_eager_s, f)
            b = getattr(r_eager_u, f)
            if a is None or b is None:
                # optional fields (hop_wait) absent on both paths
                assert a is None and b is None, f"eager {f}"
                continue
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"eager {f}",
            )

    # -- jitted: discrete fields exact, floats within ~1 ULP ---------------
    r_s = sim_scan.run(load, n, key)
    r_u = sim_unrl.run(load, n, key)
    for f in r_s._fields:
        if getattr(r_s, f) is None or getattr(r_u, f) is None:
            # optional fields (hop_wait) absent on both paths
            assert getattr(r_s, f) is None and getattr(r_u, f) is None
            continue
        a = np.asarray(getattr(r_s, f))
        b = np.asarray(getattr(r_u, f))
        if a.dtype == bool or np.issubdtype(a.dtype, np.integer):
            np.testing.assert_array_equal(a, b, err_msg=f"jit {f}")
        else:
            np.testing.assert_allclose(
                a, b, rtol=3e-7, atol=1e-12, err_msg=f"jit {f}"
            )
    return sim_scan, sim_unrl


@pytest.mark.slow
def test_tree121_equivalent():
    _assert_equivalent(_tree121())


@pytest.mark.slow
@pytest.mark.slow
def test_skewed_multitier_equivalent():
    _assert_equivalent(_multitier())


@pytest.mark.slow
@pytest.mark.slow
def test_retry_timeout_equivalent():
    _assert_equivalent(_retry_graph())


def test_retry_timeout_closed_loop_equivalent():
    _assert_equivalent(
        _retry_graph(),
        load=LoadModel(kind="closed", qps=200.0, connections=8),
    )


@pytest.mark.slow
@pytest.mark.slow
def test_chaos_equivalent():
    _assert_equivalent(
        _retry_graph(),
        chaos=(ChaosEvent(service="leaf", start_s=0.05, end_s=0.3),),
    )


@pytest.mark.slow
@pytest.mark.slow
def test_sparse_island_mix_equivalent():
    """A forced-sparse hub level keeps its unrolled specialized path
    while the levels around it scan — both executors must agree."""
    fan = 12
    doc = "services:\n"
    doc += "- name: entry\n  isEntrypoint: true\n  script:\n  - call: a\n"
    doc += "- name: a\n  script:\n  - call: hub\n"
    # the hub: a long mostly-sleep script with ONE call-bearing step —
    # its level's dense (1 x pmax) grid far exceeds the real call-slot
    # count, so a tiny sparse_level_elems forces the sparse encoding
    doc += "- name: hub\n  script:\n"
    for _ in range(10):
        doc += "  - sleep: 1ms\n"
    doc += "  - " + "\n    ".join(
        [f"- call: l{i}" for i in range(fan)]
    ) + "\n"
    for i in range(fan):
        doc += f"- name: l{i}\n  script:\n  - call: m{i}\n"
        doc += f"- name: m{i}\n  script:\n  - call: d{i}\n"
        doc += f"- name: d{i}\n"
    compiled = compile_graph(ServiceGraph.from_yaml(doc))
    sim_scan, _ = _assert_equivalent(
        compiled, params=dict(sparse_level_elems=8)
    )
    kinds = [type(s).__name__ for s in sim_scan._segments]
    # scan buckets AROUND an unrolled sparse island
    assert kinds.count("ScanBucket") >= 2
    sparse_levels = [
        d for d, lvl in enumerate(sim_scan._levels)
        if lvl.sparse is not None
    ]
    assert sparse_levels, "sparse path did not engage"


def test_run_summary_equivalent():
    compiled = _retry_graph()
    sim_scan = Simulator(compiled, SimParams(**SCAN))
    sim_unrl = Simulator(compiled, SimParams(**UNROLLED))
    s1 = sim_scan.run_summary(OPEN, 512, KEY, block_size=128)
    s2 = sim_unrl.run_summary(OPEN, 512, KEY, block_size=128)
    assert float(s1.count) == float(s2.count)
    assert float(s1.hop_events) == float(s2.hop_events)
    assert float(s1.error_count) == float(s2.error_count)
    np.testing.assert_allclose(
        float(s1.latency_sum), float(s2.latency_sum), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(s1.latency_hist), np.asarray(s2.latency_hist)
    )


def test_default_on_engages_for_deep_chain():
    """With default params a constant-width chain buckets into one scan."""
    chain = "services:\n- name: s0\n  isEntrypoint: true\n  script:\n  - call: s1\n"  # noqa: E501
    for i in range(1, 8):
        chain += f"- name: s{i}\n"
        if i < 7:
            chain += f"  script:\n  - call: s{i + 1}\n"
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(chain)))
    assert sim.params.bucketed_scan
    assert _num_scan(sim) == 1
    scan = [s for s in sim._segments if isinstance(s, ScanBucket)][0]
    assert scan.num_levels == 7  # all non-leaf levels in ONE bucket


# ---------------------------------------------------------------------------
# planner unit tests


def _shape(size, pmax=1, children=1, calls=1, attempts=1, sparse=False,
           offset=0):
    return LevelShape(size=size, pmax=pmax, children=children,
                      calls=calls, attempts=attempts, sparse=sparse,
                      offset=offset)


def test_planner_chain_single_bucket():
    shapes = [_shape(1) for _ in range(9)] + [
        _shape(1, calls=0, children=0)
    ]
    segs = plan_segments(shapes)
    assert isinstance(segs[0], ScanBucketPlan)
    assert (segs[0].d0, segs[0].d1) == (0, 8)
    assert isinstance(segs[1], UnrolledLevelPlan)  # the leaf


def test_planner_respects_waste_budget():
    # geometric growth: padding level d to level d+2's width busts 1.6x
    shapes = [
        _shape(3 ** i, children=3 ** (i + 1), calls=3 ** (i + 1))
        for i in range(4)
    ] + [_shape(81, calls=0, children=0)]
    segs = plan_segments(shapes, waste=1.2)
    assert all(isinstance(s, UnrolledLevelPlan) for s in segs)


def test_planner_sparse_and_leaf_excluded():
    shapes = [_shape(4), _shape(4, sparse=True), _shape(4), _shape(4),
              _shape(4, calls=0, children=0)]
    segs = plan_segments(shapes, waste=8.0)
    assert isinstance(segs[0], UnrolledLevelPlan)   # run of 1 before sparse
    assert isinstance(segs[1], UnrolledLevelPlan)   # the sparse island
    assert isinstance(segs[2], ScanBucketPlan)      # levels 2-3
    assert isinstance(segs[3], UnrolledLevelPlan)   # the leaf


def test_planner_disabled():
    shapes = [_shape(1) for _ in range(5)]
    segs = plan_segments(shapes, enabled=False)
    assert all(isinstance(s, UnrolledLevelPlan) for s in segs)


def test_bucket_bound_covers_carry_child():
    # sizes 2,2 with a 5-wide child level: the carry must fit the child
    shapes = [_shape(2, children=2), _shape(2, children=5),
              _shape(5, calls=0, children=0)]
    segs = plan_segments(shapes, waste=16.0)
    assert isinstance(segs[0], ScanBucketPlan)
    assert segs[0].bound_hops == 5


def test_waste_param_validation():
    with pytest.raises(ValueError):
        SimParams(level_bucket_waste=0.5)
