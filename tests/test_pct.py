"""Percentage decode/format tests.

Coverage mirrors the reference's table-driven pct/percentage_test.go.
"""
import pytest

from isotope_tpu.models.pct import (
    InvalidPercentageStringError,
    OutOfRangeError,
    Percentage,
)


@pytest.mark.parametrize(
    "s,expected",
    [
        ("0%", 0.0),
        ("100%", 1.0),
        ("50%", 0.5),
        ("0.01%", 0.0001),
        ("12.5%", 0.125),
    ],
)
def test_from_string(s, expected):
    assert Percentage.from_string(s) == pytest.approx(expected)


@pytest.mark.parametrize("s", ["", "50", "abc%", "%"])
def test_from_string_invalid(s):
    with pytest.raises(InvalidPercentageStringError):
        Percentage.from_string(s)


@pytest.mark.parametrize("s", ["101%", "-1%"])
def test_from_string_out_of_range(s):
    with pytest.raises(OutOfRangeError):
        Percentage.from_string(s)


@pytest.mark.parametrize("f,ok", [(0.0, True), (1.0, True), (0.5, True), (1.5, False), (-0.5, False)])
def test_from_float(f, ok):
    if ok:
        assert Percentage.from_float(f) == f
    else:
        with pytest.raises(OutOfRangeError):
            Percentage.from_float(f)


def test_decode_number_and_string():
    assert Percentage.decode(0.25) == 0.25
    assert Percentage.decode("25%") == 0.25


def test_str():
    # percentage.go:28-30: "%0.2f%%" of p*100.
    assert str(Percentage(0.125)) == "12.50%"
    assert str(Percentage(1.0)) == "100.00%"


def test_encode_is_number():
    assert Percentage(0.5).encode() == 0.5
