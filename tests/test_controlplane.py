"""Pilot config-push convergence model (load_test.py analogue)."""
import json

import numpy as np
import pytest

from isotope_tpu import cli
from isotope_tpu.sim.controlplane import (
    PilotModel,
    convergence_sweep,
    push_convergence,
)


def test_deterministic_closed_form():
    # no jitter: batches of push_throttle finish in lockstep
    m = PilotModel(push_throttle=4, push_jitter=0.0,
                   debounce_s=0.1, gen_s_per_endpoint=0.0,
                   push_base_s=1.0, push_s_per_endpoint=0.0)
    res = push_convergence(m, 1, 1, 10)
    # 10 proxies over 4 channels: batches end at 1.1, 2.1, 3.1
    want = [1.1] * 4 + [2.1] * 4 + [3.1] * 2
    np.testing.assert_allclose(np.sort(res.ack_times_s), want, rtol=1e-6)
    assert res.converged_fraction(1.2) == pytest.approx(0.4)
    assert res.converged_fraction(3.2) == 1.0


def test_convergence_grows_with_config_and_fleet():
    m = PilotModel()
    small = push_convergence(m, 10, 10, 50)
    big_cfg = push_convergence(m, 1000, 10, 50)
    big_fleet = push_convergence(m, 10, 10, 5000)
    assert big_cfg.max_s > small.max_s
    assert big_fleet.max_s > small.max_s
    # throttle binds: more concurrency converges faster
    wide = PilotModel(push_throttle=1000)
    assert (
        push_convergence(wide, 10, 10, 5000).max_s < big_fleet.max_s
    )


def test_sweep_rows_monotone():
    rows = convergence_sweep(PilotModel(), [10, 100, 1000], 10, 100)
    assert [r["num_entries"] for r in rows] == [10, 100, 1000]
    p99s = [r["p99_s"] for r in rows]
    assert p99s[0] < p99s[1] < p99s[2]


def test_cli_pilot_load(capsys):
    rc = cli.main(
        ["pilot-load", "--entries", "10,100", "--proxies", "20"]
    )
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    rows = [json.loads(line) for line in lines]
    assert len(rows) == 2
    assert rows[0]["proxies"] == 20
    assert rows[1]["p99_s"] >= rows[0]["p50_s"]


def test_validation():
    with pytest.raises(ValueError):
        PilotModel(push_throttle=0)
    with pytest.raises(ValueError):
        push_convergence(PilotModel(), 1, 1, 0)
