"""Static analysis (`isotope-tpu vet`): seeded-defect fixtures.

Each planted defect class must surface with its expected rule id and a
nonzero exit, the shipped examples must vet clean, and — load-bearing —
the jaxpr audit must be trace-only: no jit first-call, no backend
compile, no engine execution.
"""
import json

import jax
import jax.numpy as jnp
import pytest

from isotope_tpu import cli, telemetry
from isotope_tpu.analysis import (
    RULES,
    Report,
    suppression_patterns,
    vet_simulator,
    vet_topology_path,
)
from isotope_tpu.analysis import costmodel, jaxpr_audit, topo_lint
from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import LoadModel
from isotope_tpu.sim.engine import Simulator

OPEN = LoadModel(kind="open", qps=100.0)


def _graph(doc):
    return ServiceGraph.decode(doc)


def _write_topo(tmp_path, doc, name="topo.yaml"):
    import yaml

    p = tmp_path / name
    p.write_text(yaml.safe_dump(doc))
    return str(p)


CHAIN = {
    "services": [
        {"name": "a", "isEntrypoint": True, "script": [{"call": "b"}]},
        {"name": "b"},
    ]
}


# -- topology linter --------------------------------------------------------


def test_unreachable_service_is_an_error():
    g = _graph({
        "services": [
            {"name": "a", "isEntrypoint": True,
             "script": [{"call": "b"}]},
            {"name": "b"},
            {"name": "orphan"},
        ]
    })
    findings = topo_lint.lint_graph(g)
    rules = {f.rule for f in findings}
    assert "VET-T001" in rules
    (f,) = [f for f in findings if f.rule == "VET-T001"]
    assert f.severity == "error"
    assert f.path == "services[2]"
    assert "orphan" in f.message


def test_cycle_reported_with_path():
    g = _graph({
        "services": [
            {"name": "a", "isEntrypoint": True,
             "script": [{"call": "b"}]},
            {"name": "b", "script": [{"call": "a"}]},
        ]
    })
    findings = topo_lint.lint_graph(g)
    (f,) = [f for f in findings if f.rule == "VET-T002"]
    assert "a -> b -> a" in f.message


def test_replica_and_error_rate_bounds():
    g = _graph({
        "services": [
            {"name": "a", "isEntrypoint": True, "numReplicas": 0,
             "errorRate": 1.0},
        ]
    })
    rules = {f.rule: f.severity for f in topo_lint.lint_graph(g)}
    assert rules["VET-T004"] == "error"
    assert rules["VET-T005"] == "warn"


def test_no_entrypoint():
    g = _graph({"services": [{"name": "a"}]})
    (f,) = topo_lint.lint_graph(g)
    assert f.rule == "VET-T003" and f.severity == "error"


@pytest.mark.parametrize("example", [
    "examples/topologies/canonical.yaml",
    "examples/topologies/chain-3-services.yaml",
    "examples/topologies/tree-13-services.yaml",
    "examples/topologies/realistic-star-50.yaml",
    "examples/topologies/realistic-auxiliary-services-50.yaml",
    "examples/topologies/two-cluster-canonical.yaml",
    "examples/topologies/canonical-errors.yaml",
])
def test_shipped_examples_vet_clean(example, monkeypatch):
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    monkeypatch.delenv("ISOTOPE_VET_DEVICE_BYTES", raising=False)
    report = vet_topology_path(example, load=OPEN)
    assert report.errors == [], [f.render() for f in report.errors]


def test_cli_unreachable_fixture_exits_nonzero(tmp_path, capsys):
    path = _write_topo(tmp_path, {
        "services": [
            {"name": "a", "isEntrypoint": True},
            {"name": "dead"},
        ]
    })
    rc = cli.main(["vet", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "VET-T001" in out


# -- jaxpr auditor ----------------------------------------------------------


def test_audit_flags_injected_host_callback_and_f64_leak():
    def defective(x):
        jax.debug.callback(lambda v: None, x)
        y = jax.lax.convert_element_type(x, jnp.float64)
        return (y * 2.0).astype(jnp.float32)

    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(defective)(
            jax.ShapeDtypeStruct((8,), jnp.float32)
        )
    rules = {f.rule for f in jaxpr_audit.audit_jaxpr(closed)}
    assert "VET-J001" in rules
    assert "VET-J002" in rules

    def clean(x):
        return x * 2.0

    closed = jax.make_jaxpr(clean)(jax.ShapeDtypeStruct((8,), jnp.float32))
    assert jaxpr_audit.audit_jaxpr(closed) == []


def test_cli_injected_defects_report_rule_ids(monkeypatch, capsys):
    monkeypatch.setenv("ISOTOPE_VET_INJECT", "callback,f64")
    rc = cli.main(["vet", "examples/topologies/chain-3-services.yaml"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "VET-J001" in out and "VET-J002" in out


def test_engine_program_audits_clean(monkeypatch):
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    sim = Simulator(compile_graph(_graph(CHAIN)))
    findings, closed, traced_n = jaxpr_audit.audit_simulator(sim, OPEN)
    assert [f for f in findings if f.severity == "error"] == []
    assert closed is not None
    assert traced_n == 8


def test_cache_signature_audit_catches_id_repr():
    class Opaque:
        pass

    findings = jaxpr_audit.audit_cache_signature(
        ("engine-v1", ("scan", 0), repr(Opaque()))
    )
    assert any(f.rule == "VET-J004" for f in findings)
    # the real engine signature must be hazard-free
    sim = Simulator(compile_graph(_graph(CHAIN)))
    assert jaxpr_audit.audit_cache_signature(sim.signature) == []


def test_audit_is_trace_only(monkeypatch):
    """Pinned: the jaxpr audit performs NO device execution — no jit
    first-call, no backend compile, and the engine entry points are
    never invoked."""
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)

    def boom(*a, **k):  # pragma: no cover - must never run
        raise AssertionError("vet executed the engine")

    monkeypatch.setattr(Simulator, "run", boom)
    monkeypatch.setattr(Simulator, "run_summary", boom)
    telemetry.reset()
    report = vet_topology_path(
        "examples/topologies/tree-13-services.yaml", load=OPEN,
    )
    assert report.errors == []
    assert telemetry.counter_get("jit_first_calls") == 0.0
    assert telemetry.phase_seconds("compile.backend") == 0.0


# -- pre-flight cost model --------------------------------------------------


def _sim_and_estimate(device_bytes=None):
    sim = Simulator(compile_graph(_graph(CHAIN)))
    report = vet_simulator(
        sim, OPEN, block_requests=4096, device_bytes=device_bytes,
    )
    return sim, report


def test_cost_model_estimates_are_positive():
    sim = Simulator(compile_graph(_graph(CHAIN)))
    closed, n = jaxpr_audit.trace_entry(sim, OPEN)
    assert n == 8
    jc = costmodel.jaxpr_cost(closed)
    assert jc.flops > 0
    assert jc.peak_bytes > 0
    assert jc.critical_path > 0
    rows = costmodel.segment_table(sim, 4096)
    assert len(rows) == len(sim._segments)
    assert all(r["elems"] > 0 for r in rows)


def test_closed_loop_estimate_scales_by_actual_traced_n():
    """A 64-connection closed-loop trace runs at n=64, not n=8: the
    estimate must divide by the REAL traced count (a mismatch inflated
    closed-loop peak bytes 8x, spuriously tripping VET-M*)."""
    sim = Simulator(compile_graph(_graph(CHAIN)))
    closed_load = LoadModel(kind="closed", qps=100.0, connections=64)
    rep_open = vet_simulator(sim, OPEN, block_requests=4096)
    rep_closed = vet_simulator(sim, closed_load, block_requests=4096)
    po = rep_open.meta["cost"]["peak_bytes_at_block"]
    pc = rep_closed.meta["cost"]["peak_bytes_at_block"]
    assert pc == pytest.approx(po, rel=0.5)  # same order, not ~8x


def test_lint_survives_deep_chains():
    """The cycle walk is iterative: a 2000-service chain must lint
    clean, not blow the recursion limit."""
    n = 2000
    g = _graph({"services": (
        [{"name": "s0", "isEntrypoint": True,
          "script": [{"call": "s1"}]}]
        + [{"name": f"s{i}", "script": [{"call": f"s{i + 1}"}]}
           for i in range(1, n - 1)]
        + [{"name": f"s{n - 1}"}]
    )})
    assert topo_lint.lint_graph(g) == []


def test_malformed_yaml_is_a_finding_not_a_crash(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("services: [unclosed\n")
    report = vet_topology_path(str(bad))
    (f,) = report.findings
    assert f.rule == "VET-C001" and f.severity == "error"
    assert cli.main(["vet", str(bad)]) == 1


def test_toml_report_carries_cost_meta(tmp_path):
    topo = _write_topo(tmp_path, CHAIN, "chain.yaml")
    cfg = tmp_path / "sweep.toml"
    cfg.write_text(f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [50]
num_concurrent_connections = [4]
duration = "10s"
load_kind = "open"
""")
    from isotope_tpu.analysis import vet_config_path

    report = vet_config_path(cfg)
    assert str(topo) in report.meta
    assert report.meta[str(topo)]["cost"]["peak_bytes_at_block"] > 0


def test_oversized_topology_trips_oom_rung_selection():
    # capacity far below the estimate: every on-device rung busts ->
    # VET-M001 (error) and the last rung (cpu-eager) is pre-selected
    _, report = _sim_and_estimate(device_bytes=65536.0)
    assert any(f.rule == "VET-M001" for f in report.findings)
    assert report.meta["start_rung"] == 2
    assert report.meta["rung_names"][2] == "cpu-eager"

    # capacity that fits HALF the block but not the whole block ->
    # VET-M002 (warn) recommends the half-block rung
    peak = report.meta["cost"]["peak_bytes_at_block"]
    cap = peak * 0.7 / costmodel.CAPACITY_FILL
    _, report2 = _sim_and_estimate(device_bytes=cap)
    assert any(f.rule == "VET-M002" for f in report2.findings)
    assert report2.meta["start_rung"] == 1

    # ample capacity: clean, rung 0
    _, report3 = _sim_and_estimate(device_bytes=peak * 100.0)
    assert report3.meta["start_rung"] == 0
    assert not any(
        f.rule.startswith("VET-M") for f in report3.findings
    )


@pytest.mark.slow
@pytest.mark.slow
def test_runner_gate_preselects_rung_and_records_degraded(
    tmp_path, monkeypatch
):
    from isotope_tpu.runner.config import ExperimentConfig
    from isotope_tpu.runner.config import DEFAULT_ENVIRONMENTS
    from isotope_tpu.runner.run import run_experiment

    monkeypatch.setenv("ISOTOPE_VET_DEVICE_BYTES", "65536")
    topo = _write_topo(tmp_path, CHAIN)
    config = ExperimentConfig(
        topology_paths=(topo,),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(50.0,), connections=(4,), duration_s=1.0,
        load_kind="open", num_requests=128,
    )
    (res,) = run_experiment(config, vet="on")
    assert not res.failed
    # the memory verdict started the ladder degraded — recorded exactly
    # like a ladder descent (bench_regress keys on degraded_to)
    assert res.degraded_to == "cpu-eager"


def test_runner_gate_blocks_defective_topology(tmp_path):
    from isotope_tpu.runner.config import ExperimentConfig
    from isotope_tpu.runner.config import DEFAULT_ENVIRONMENTS
    from isotope_tpu.runner.run import run_experiment

    topo = _write_topo(tmp_path, {
        "services": [
            {"name": "a", "isEntrypoint": True},
            {"name": "dead"},
        ]
    })
    config = ExperimentConfig(
        topology_paths=(topo,),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(50.0,), connections=(4,), duration_s=1.0,
        load_kind="open", num_requests=128,
    )
    (res,) = run_experiment(config, vet="on")
    assert res.failed
    assert "VET-T001" in res.error

    # gate off: the same topology runs fine (dead capacity is legal)
    (res_off,) = run_experiment(config)
    assert not res_off.failed


# -- suppression ------------------------------------------------------------


def test_rules_registry_and_suppression():
    assert "VET-T001" in RULES and "VET-M001" in RULES
    with pytest.raises(ValueError, match="unknown vet rule"):
        suppression_patterns("VET-X999")
    pats = suppression_patterns("VET-J003,VET-T00*")
    r = Report(suppress=pats)
    r.add(topo_lint.Finding("VET-T001", "error", "x"))
    r.add(topo_lint.Finding("VET-M001", "error", "y"))
    assert [f.rule for f in r.findings] == ["VET-M001"]
    assert [f.rule for f in r.suppressed] == ["VET-T001"]
    assert [f.rule for f in r.blocking()] == ["VET-M001"]
    assert r.blocking(nonblocking_rules=("VET-M001",)) == []


def test_cli_suppression_silences_exit(tmp_path):
    path = _write_topo(tmp_path, {
        "services": [
            {"name": "a", "isEntrypoint": True},
            {"name": "dead"},
        ]
    })
    assert cli.main(["vet", path]) == 1
    assert cli.main(["vet", path, "--suppress", "VET-T001"]) == 0


def test_grad_rules_registered_and_unknown_raises():
    for rule in ("VET-G001", "VET-G002", "VET-G003", "VET-G004"):
        assert rule in RULES
    suppression_patterns("VET-G*")  # valid family glob
    with pytest.raises(ValueError, match="unknown vet rule"):
        suppression_patterns("VET-G999")


def test_cli_grad_suppression_silences_exit(tmp_path, monkeypatch):
    """`--suppress 'VET-G*'` silences the grad gate: under --strict
    the VET-G warnings (gradient-dead knob, vacuous objectives)
    promote to a nonzero exit, and the family glob restores 0."""
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    path = _write_topo(tmp_path, CHAIN)
    assert cli.main(["vet", path, "--strict"]) == 0
    assert cli.main(["vet", "--grad", "--strict", path]) == 1
    assert cli.main(
        ["vet", "--grad", "--strict", "--suppress", "VET-G*", path]
    ) == 0


def test_strict_promotes_warnings(tmp_path):
    path = _write_topo(tmp_path, {
        "services": [
            {"name": "a", "isEntrypoint": True, "errorRate": 1.0},
        ]
    })
    assert cli.main(["vet", path]) == 0          # warn only
    assert cli.main(["vet", path, "--strict"]) == 1


# -- config (TOML) linter ---------------------------------------------------


def test_config_lint_rules(tmp_path):
    topo = _write_topo(tmp_path, CHAIN, "chain.yaml")
    cfg = tmp_path / "sweep.toml"
    cfg.write_text(f"""
topology_paths = ["{topo}", "missing.yaml"]
environments = ["NONE"]

[client]
qps = [50]
num_concurrent_connections = [4]
duration = "10s"
load_kind = "open"

[[chaos]]
service = "nope"
start = "1s"
end = "2s"

[[churn]]
service = "b"
period = "60s"
weights = [1.0, 0.5]
""")
    findings, graphs = topo_lint.lint_config(
        __import__(
            "isotope_tpu.runner.config", fromlist=["load_toml"]
        ).load_toml(cfg)
    )
    rules = {f.rule for f in findings}
    assert "VET-C001" in rules   # missing.yaml
    assert "VET-C003" in rules   # chaos on unknown service
    assert "VET-C004" in rules   # churn period > duration
    assert str(topo) in graphs


def test_cli_vet_toml(tmp_path, capsys):
    topo = _write_topo(tmp_path, CHAIN, "chain.yaml")
    cfg = tmp_path / "sweep.toml"
    cfg.write_text(f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [50]
num_concurrent_connections = [4]
duration = "10s"
load_kind = "open"
""")
    rc = cli.main(["vet", "--json", str(cfg)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["findings"] == []


# -- loader key-path errors (satellite) -------------------------------------


def test_decode_errors_carry_key_paths():
    with pytest.raises(ValueError) as ei:
        ServiceGraph.decode({
            "services": [
                {"name": "a", "isEntrypoint": True},
                {"name": "b", "script": [{"call": "a"},
                                         {"sleep": 5}]},
            ]
        })
    assert "services[1].script[1].sleep" in str(ei.value)

    with pytest.raises(ValueError) as ei:
        ServiceGraph.decode({
            "defaults": {"requestSize": "bogus"},
            "services": [],
        })
    assert "defaults.requestSize" in str(ei.value)


def test_toml_errors_carry_key_paths(tmp_path):
    from isotope_tpu.runner.config import load_toml

    cfg = tmp_path / "bad.toml"
    cfg.write_text("""
topology_paths = []

[[chaos]]
service = "a"
start = "xx"
end = "2s"
""")
    with pytest.raises(ValueError) as ei:
        load_toml(cfg)
    assert "chaos[0].start" in str(ei.value)


# -- telemetry & bench-gate plumbing ----------------------------------------


def test_vet_counters_render_as_first_class_series():
    telemetry.reset()
    sim = Simulator(compile_graph(_graph(CHAIN)))
    vet_simulator(sim, OPEN, block_requests=1024, trace=False)
    assert telemetry.counter_get("vet_runs_total") == 1.0
    blk = telemetry.summary_block()
    assert blk["vet_runs"] == 1
    assert "vet_errors" in blk
    text = telemetry.prometheus_text()
    assert "isotope_engine_vet_runs_total" in text
    # a record that never vetted must NOT carry the keys (presence is
    # how bench_regress distinguishes "clean" from "never ran")
    telemetry.reset()
    assert "vet_errors" not in telemetry.summary_block()


def test_bench_regress_vet_gate(monkeypatch):
    import tools.bench_regress as br

    prev = {"value": 1.0, "extra": {
        "svc1000": 2.0,
        "svc1000_telemetry": {"vet_errors": 0, "vet_runs": 1},
    }}
    new_bad = {"value": 1.0, "extra": {
        "svc1000": 2.0,
        "svc1000_telemetry": {"vet_errors": 2, "vet_runs": 1},
    }}
    monkeypatch.delenv("BENCH_REGRESS_VET_GATE", raising=False)
    assert br.vet_failures(prev, new_bad) == []      # gate disarmed
    monkeypatch.setenv("BENCH_REGRESS_VET_GATE", "1")
    assert br.vet_failures(prev, new_bad) == ["svc1000.vet_errors"]
    assert br.vet_failures(prev, prev) == []         # unchanged: clean
    # baseline without vet data: skipped, never read as zero
    no_vet = {"value": 1.0, "extra": {
        "svc1000": 2.0, "svc1000_telemetry": {},
    }}
    assert br.vet_failures(no_vet, new_bad) == []


# -- fault-injection eager validation (satellite) ---------------------------


def test_fault_site_validation_lists_valid_sites():
    from isotope_tpu.resilience import faults

    with pytest.raises(ValueError) as ei:
        faults.FaultPlan.parse("oom:engine.rnu:1")
    msg = str(ei.value)
    for site in faults.VALID_SITES:
        assert site in msg
    faults.clear()
