"""Suite pipeline + monitor sink (run_benchmark_job.sh / webhook.go
parity): run configs -> publish tree -> monitor rows -> manifest."""
import json
import pathlib

import pytest

from isotope_tpu import cli
from isotope_tpu.metrics.alarms import Alarm, Query
from isotope_tpu.metrics.monitor import (
    STATUS_ALARM,
    STATUS_OK,
    MonitorSink,
    evaluate,
    monitor_run,
)
from isotope_tpu.metrics.query import MetricStore
from isotope_tpu.runner.suite import run_suite, suite_id

TOPO = pathlib.Path(__file__).parent.parent / "examples/topologies/canonical.yaml"


def write_cfg(tmp_path, name, qps):
    cfg = tmp_path / name
    cfg.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE"]

[client]
qps = [{qps}]
num_concurrent_connections = [8]
duration = "60s"
load_kind = "open"

[sim]
num_requests = 2000
seed = 3
"""
    )
    return cfg


# -- monitor sink ----------------------------------------------------------

EXPO = 'errs_total{service="a"} 5\nok_total{service="a"} 100\n'
STORE = MetricStore.from_text(EXPO, duration_s=10.0)


def q(expr, fires, msg="bad"):
    return Query("check", expr, Alarm(fires, msg), None)


def test_monitor_rows_ok_and_alarm(tmp_path):
    sink = MonitorSink(tmp_path / "status.jsonl")
    rows = monitor_run(
        STORE,
        sink,
        [
            q("rate(errs_total[1m])", lambda v: v > 0, "errors!"),
            q("rate(ok_total[1m])", lambda v: v <= 0, "no traffic"),
        ],
        run_label="r1",
    )
    assert [r.status for r in rows] == [STATUS_ALARM, STATUS_OK]
    assert rows[0].value == pytest.approx(0.5)
    assert rows[0].detail == "errors!"
    # persisted and readable
    assert [r.status for r in sink.read()] == [STATUS_ALARM, STATUS_OK]
    assert len(sink.alarms()) == 1


def test_monitor_running_query_gate():
    rows = evaluate(
        [
            Query(
                "gated", "rate(errs_total[1m])",
                Alarm(lambda v: True, "x"),
                'sum(ok_total{service="nosuch"})',
            )
        ],
        STORE,
    )
    assert rows == []


def test_suite_id_format():
    from datetime import datetime, timezone

    d = datetime(2026, 7, 30, tzinfo=timezone.utc)
    assert suite_id("master", "sim", "dev", d) == "20260730_sim_master_dev"


def test_suite_publishes_tree_and_manifest(tmp_path):
    # both below the 50-mcore standard CPU limit (the busiest service
    # sees 2x the entry rate at ~77us/req)
    c1 = write_cfg(tmp_path, "latency.toml", 200)
    c2 = write_cfg(tmp_path, "cpu_mem.toml", 250)
    result = run_suite([str(c1), str(c2)], tmp_path / "pub",
                       id="20260730_sim_master_dev")
    pub = result.publish_dir
    assert pub == tmp_path / "pub" / "20260730_sim_master_dev"
    for stem in ("latency", "cpu_mem"):
        assert (pub / stem / "benchmark.csv").exists()
        assert (pub / stem / "results.jsonl").exists()
        assert (pub / stem / "report.html").exists()
    manifest = json.loads((pub / "manifest.json").read_text())
    assert manifest["total_runs"] == 2
    assert [c["name"] for c in manifest["configs"]] == [
        "latency", "cpu_mem"
    ]
    # the clean canonical runs raise no alarms
    assert manifest["total_alarms"] == 0
    status = (pub / "monitor_status.jsonl").read_text().splitlines()
    # 4 standard checks per run x 2 runs
    assert len(status) == 8
    assert all(json.loads(s)["status"] == STATUS_OK for s in status)


def test_suite_resumes_completed_configs(tmp_path):
    c1 = write_cfg(tmp_path, "latency.toml", 200)
    run_suite([str(c1)], tmp_path / "pub", id="x")
    pub = tmp_path / "pub" / "x"
    rows1 = (pub / "monitor_status.jsonl").read_text().splitlines()
    ran = []
    run_suite([str(c1)], tmp_path / "pub", id="x", progress=ran.append)
    assert ran == []  # checkpointed sweep replays
    # re-running the same publish id must not append duplicate monitor
    # rows (the sink restarts fresh each invocation)
    rows2 = (pub / "monitor_status.jsonl").read_text().splitlines()
    assert len(rows2) == len(rows1)


def test_suite_subsecond_run_rates_are_finite(tmp_path):
    # sub-second runs used to truncate ActualDuration to 0 s, zeroing
    # every rate() so the requests-sanity alarm fired spuriously; the
    # store must be built from the nanosecond duration instead
    cfg = tmp_path / "short.toml"
    cfg.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE"]

[client]
qps = [200]
num_concurrent_connections = [8]
duration = "500ms"
load_kind = "open"

[sim]
num_requests = 100
seed = 3
"""
    )
    result = run_suite([str(cfg)], tmp_path / "pub", id="sub")
    assert result.manifest["total_alarms"] == 0


def test_suite_cli_exit_code_on_alarm(tmp_path, capsys):
    c1 = write_cfg(tmp_path, "latency.toml", 200)
    rc = cli.main(
        ["suite", str(c1), "-o", str(tmp_path / "pub"), "--id", "y"]
    )
    assert rc == 0
    assert "1 runs across 1 configs" in capsys.readouterr().err
    # an absurd CPU limit makes the standard CPU check fire
    rc = cli.main(
        ["suite", str(c1), "-o", str(tmp_path / "pub2"), "--id", "z",
         "--cpu-limit", "0.0001"]
    )
    assert rc == 1


def test_suite_publish_id_carries_loadgen(tmp_path):
    # download.py:56-62 id format: <date>_<loadgen>_<branch>_<ver>
    cfg = tmp_path / "nh.toml"
    cfg.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE"]

[client]
loadgen = "nighthawk"
qps = [200]
num_concurrent_connections = [8]
duration = "30s"

[sim]
num_requests = 1500
"""
    )
    result = run_suite([str(cfg)], tmp_path / "pub")
    assert "_nighthawk_" in result.publish_dir.name
    assert result.manifest["loadgen"] == "nighthawk"


def test_loadgen_validation(tmp_path):
    from isotope_tpu.runner.config import load_toml

    base = f"""
topology_paths = ["{TOPO}"]
environments = ["NONE"]

[client]
qps = [100]
num_concurrent_connections = [4]
duration = "30s"
"""
    ok = tmp_path / "ok.toml"
    ok.write_text(base + 'loadgen = "nighthawk"\n')
    c = load_toml(ok)
    assert c.loadgen == "nighthawk"
    assert c.load_kind == "open"  # nighthawk implies open loop

    bad = tmp_path / "bad.toml"
    bad.write_text(
        base + 'loadgen = "nighthawk"\nload_kind = "closed"\n'
    )
    with pytest.raises(ValueError, match="open-loop generator"):
        load_toml(bad)

    unk = tmp_path / "unk.toml"
    unk.write_text(base + 'loadgen = "wrk2"\n')
    with pytest.raises(ValueError, match="unknown loadgen"):
        load_toml(unk)


def test_bigquery_exporter_writes_datafile(tmp_path):
    # the collector's upload hook (fortio.py:235-242): the exporter
    # must produce the exact NDJSON datafile `bq insert` consumes
    from isotope_tpu.runner.config import load_toml
    from isotope_tpu.runner.run import run_experiment

    cfg = write_cfg(tmp_path, "exp.toml", 200)
    out = tmp_path / "out"
    run_experiment(
        load_toml(cfg), out_dir=str(out),
        export=["bigquery:proj.perf.results"],
    )
    lines = (out / "bq_rows.json").read_text().splitlines()
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert "DurationHistogram" in doc and "ActualQPS" in doc
    script = (out / "bq_insert.sh").read_text()
    assert "bq insert proj.perf.results bq_rows.json" in script


def test_exporter_registry_errors_and_extension(tmp_path):
    from isotope_tpu.metrics.export import (
        ExportError,
        register_exporter,
        resolve_exporter,
        run_exporters,
    )

    with pytest.raises(ExportError, match="unknown exporter"):
        resolve_exporter("spanner")
    with pytest.raises(ExportError, match="needs a table"):
        resolve_exporter("bigquery")

    seen = {}
    register_exporter(
        "testsink",
        lambda arg: (lambda results, out_dir: seen.setdefault(
            "call", (arg, len(list(results)))
        ) and "ok" or "ok"),
    )
    assert run_exporters(["testsink:xyz"], [1, 2], tmp_path) == ["ok"]
    assert seen["call"] == ("xyz", 2)
