"""ServiceGraph decode + validation tests.

Coverage mirrors the reference's graph/unmarshal_test.go end-to-end fixture
(defaults inheritance) and validation.go error cases.
"""
import pytest

from isotope_tpu.models.graph import (
    NestedConcurrentCommandError,
    RequestToUndefinedServiceError,
    ServiceGraph,
)
from isotope_tpu.models.script import (
    ConcurrentCommand,
    RequestCommand,
    SleepCommand,
)
from isotope_tpu.models.size import ByteSize
from isotope_tpu.models.svctype import ServiceType

FULL_YAML = """
defaults:
  type: http
  numReplicas: 2
  errorRate: 0.1%
  responseSize: 512
  requestSize: 128
services:
- name: a
- name: b
  type: grpc
  numReplicas: 3
  errorRate: 5%
  responseSize: 1k
- name: c
  isEntrypoint: true
  script:
  - sleep: 100ms
  - call: a
  - call: {service: b, size: 256, probability: 50}
  - - call: a
    - call: b
"""


def test_decode_defaults_inheritance():
    g = ServiceGraph.from_yaml(FULL_YAML)
    a, b, c = g.services

    assert a.name == "a"
    assert a.type == ServiceType.HTTP
    assert a.num_replicas == 2
    assert float(a.error_rate) == pytest.approx(0.001)
    assert a.response_size == 512
    assert a.script == []

    assert b.type == ServiceType.GRPC
    assert b.num_replicas == 3
    assert float(b.error_rate) == pytest.approx(0.05)
    assert b.response_size == 1024

    assert c.is_entrypoint
    assert c.script[0] == SleepCommand(0.1)
    # string-form call inherits default requestSize 128
    assert c.script[1] == RequestCommand(service_name="a", size=ByteSize(128))
    assert c.script[2] == RequestCommand(
        service_name="b", size=ByteSize(256), probability=50
    )
    assert isinstance(c.script[3], ConcurrentCommand)


def test_undefined_callee_rejected():
    with pytest.raises(RequestToUndefinedServiceError):
        ServiceGraph.from_yaml(
            """
services:
- name: a
  script:
  - call: ghost
"""
        )


def test_nested_concurrent_rejected():
    with pytest.raises(NestedConcurrentCommandError):
        ServiceGraph.from_yaml(
            """
services:
- name: a
- name: b
  script:
  - - call: a
    - - call: a
      - call: a
"""
        )


def test_service_requires_name():
    with pytest.raises(ValueError):
        ServiceGraph.from_yaml("services:\n- type: http\n")


def test_canonical_topology(tmp_path):
    g = ServiceGraph.from_yaml_file("examples/topologies/canonical.yaml")
    assert g.service_names() == ["a", "b", "c", "d"]
    (entry,) = g.entrypoints()
    assert entry.name == "d"
    # concurrent first step, then a sequential call
    assert isinstance(entry.script[0], ConcurrentCommand)
    assert entry.script[1].service_name == "b"
    # defaults: 1 KB sizes, 3 rbac policies
    assert g.services[0].response_size == 1024
    assert g.services[0].num_rbac_policies == 3


def test_yaml_roundtrip():
    g = ServiceGraph.from_yaml(FULL_YAML)
    again = ServiceGraph.from_yaml(g.to_yaml())
    assert again.services == g.services


def test_roundtrip_with_overridden_defaults():
    # Regression: a service field explicitly equal to a BUILT-IN default must
    # survive encode/decode when the graph-level default differs.
    g = ServiceGraph.from_yaml(
        """
defaults:
  numReplicas: 3
  responseSize: 10k
services:
- name: a
  numReplicas: 1
  responseSize: 0
- name: b
"""
    )
    again = ServiceGraph.from_yaml(g.to_yaml())
    assert again.services == g.services
    assert again.services[0].num_replicas == 1
    assert int(again.services[0].response_size) == 0
    assert again.services[1].num_replicas == 3


def test_empty_services_key():
    g = ServiceGraph.from_yaml("services:\n")
    assert len(g) == 0


def test_defaults_script_does_not_inherit_request_size():
    # unmarshal.go:30-43: the defaults block is parsed before
    # DefaultRequestCommand is installed, so calls in the defaults script
    # get size 0, not requestSize.
    g = ServiceGraph.from_yaml(
        """
defaults:
  requestSize: 10k
  script:
  - call: a
services:
- name: a
- name: b
"""
    )
    assert g.services[1].script[0].size == 0
    # ...while calls in a service's own script DO inherit requestSize.
    g2 = ServiceGraph.from_yaml(
        """
defaults:
  requestSize: 10k
services:
- name: a
- name: b
  script:
  - call: a
"""
    )
    assert g2.services[1].script[0].size == 10240


def test_strict_int_fields():
    for doc in (
        "services:\n- name: a\n  numReplicas: true\n",
        "services:\n- name: a\n  numReplicas: 2.9\n",
        "defaults:\n  numRbacPolicies: 1.5\nservices:\n- name: a\n",
    ):
        with pytest.raises(ValueError):
            ServiceGraph.from_yaml(doc)
