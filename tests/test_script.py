"""Script / command decode tests.

Coverage mirrors the reference's script/{script,sleep_command,
request_command,concurrent_command}_test.go table-driven suites.
"""
import pytest
import yaml

from isotope_tpu.models.script import (
    ConcurrentCommand,
    InvalidCommandError,
    MultipleKeysInCommandError,
    RequestCommand,
    Script,
    SleepCommand,
    UnknownCommandKeyError,
    decode_command,
)
from isotope_tpu.models.size import ByteSize

NO_DEFAULT = RequestCommand(service_name="")


def decode(doc, default=NO_DEFAULT):
    return Script.decode(yaml.safe_load(doc), default)


def test_sleep_command():
    (cmd,) = decode("- sleep: 100ms")
    assert cmd == SleepCommand(0.1)


def test_call_string_form():
    (cmd,) = decode("- call: a")
    assert cmd == RequestCommand(service_name="a")


def test_call_string_form_inherits_default_size():
    default = RequestCommand(service_name="", size=ByteSize(128))
    (cmd,) = decode("- call: a", default)
    assert cmd.size == 128


def test_call_object_form():
    (cmd,) = decode("- call: {service: b, size: 1k, probability: 30}")
    assert cmd == RequestCommand(service_name="b", size=ByteSize(1024), probability=30)
    assert cmd.send_probability == pytest.approx(0.3)


def test_probability_zero_means_always():
    (cmd,) = decode("- call: a")
    assert cmd.probability == 0
    assert cmd.send_probability == 1.0


@pytest.mark.parametrize("p", [-1, 101])
def test_probability_out_of_range(p):
    with pytest.raises(InvalidCommandError):
        decode(f"- call: {{service: a, probability: {p}}}")


def test_concurrent_command_from_list():
    (cmd,) = decode(
        """
- - call: a
  - call: b
  - sleep: 10ms
"""
    )
    assert isinstance(cmd, ConcurrentCommand)
    assert len(cmd) == 3
    assert cmd[0] == RequestCommand(service_name="a")
    assert cmd[2] == SleepCommand(0.01)


def test_sequential_script_order():
    script = decode(
        """
- sleep: 10ms
- call: a
- call: b
"""
    )
    assert [type(c) for c in script] == [SleepCommand, RequestCommand, RequestCommand]


def test_multiple_keys_error():
    with pytest.raises(MultipleKeysInCommandError):
        decode_command({"sleep": "1s", "call": "a"}, NO_DEFAULT)


def test_unknown_key_error():
    with pytest.raises(UnknownCommandKeyError):
        decode_command({"jump": "1s"}, NO_DEFAULT)


def test_encode_roundtrip():
    doc = """
- sleep: 100ms
- call: {service: a, size: 1k, probability: 30}
- - call: b
  - call: c
"""
    script = decode(doc)
    encoded = script.encode()
    again = Script.decode(encoded, NO_DEFAULT)
    assert again == script
