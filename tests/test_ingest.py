"""Trace-driven ingest (isotope_tpu/ingest/): readers -> fitters ->
isotope-ingest/v1 artifact, plus the self-closure pin.

Fixture expectations are hand-derived from the estimator laws the
fitters docstring states (PAPER.md service semantics):

- ``tests/data/ingest/sample.prom``: gw (10ms sojourn, 2ms station
  CPU, 1% errors) calling auth twice — the observed edge ratio
  11880/6000 = 1.98 under-counts by gw's 1% error-skip, so the
  corrected ratio is exactly 2.0; gw's sleep is the sojourn residual
  10ms - 2 x (3ms + wire) - 2ms station ~ 1ms.
- ``tests/data/ingest/envoy_stats.json``: ingress -> frontend ->
  backend from cluster stats; 24/1200 = 2% frontend errors and
  2352/1200/0.98 = 2.0 corrected fan-out; no timestamps, so rates
  need --duration.
- ``tests/data/ingest/trace.csv``: 40 traces of client -> api ->
  {db, cache} with overlapping sibling spans — api's self-time is
  rt minus the UNION of child intervals (50 - 20 = 30ms, not
  50 - 40), and the overlap marks api's calls as a concurrent group.

The closure test runs the full loop on a live simulation (the same
pin ``make ingest-smoke`` drives at power-law scale).
"""
import copy
import json
import pathlib

import pytest

from isotope_tpu.analysis.topo_lint import lint_graph, lint_ingest
from isotope_tpu.ingest import (
    CLOSURE_TOLERANCES,
    FitOptions,
    check_doc,
    closure_check,
    fit,
    format_report,
    load_doc,
    read_path,
    read_prometheus,
)
from isotope_tpu.ingest import report as report_mod
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import DEFAULT_CPU_TIME_S

DATA = pathlib.Path(__file__).parent / "data" / "ingest"


def _coverage_partitions(cov) -> None:
    assert cov.lines_total == (
        cov.lines_blank + cov.lines_comment + cov.lines_parsed
        + cov.lines_malformed
    )
    assert cov.samples_used + cov.samples_ignored == cov.lines_parsed


# -- prometheus reader + fit -------------------------------------------


@pytest.fixture(scope="module")
def prom_fit():
    obs = read_path(str(DATA / "sample.prom"))
    return obs, fit(obs, FitOptions(label="prom", duration_s=60.0))


def test_prom_coverage_partitions_every_line(prom_fit):
    obs, _ = prom_fit
    (cov,) = obs.inputs
    _coverage_partitions(cov)
    # 21 physical lines: 2 comments, 17 samples, 1 malformed, 1 blank
    assert cov.lines_total == 21
    assert cov.lines_comment == 2
    assert cov.lines_parsed == 17
    assert cov.lines_malformed == 1
    assert cov.lines_blank == 1
    # the vendor family is ignored WITH accounting, never dropped
    assert cov.samples_used == 16
    assert cov.samples_ignored == 1
    assert any("vendor_go_gc" in n for n in cov.notes)
    (line_no, text) = cov.malformed_examples[0]
    assert "not a metric" in text


def test_prom_error_skip_corrected_fanout(prom_fit):
    _, fr = prom_fit
    assert fr.entry == "gw"
    # observed 11880/6000 = 1.98; gw's 1% error-skip corrects to 2.0
    assert fr.edges[("gw", "auth")] == pytest.approx(2.0)
    assert fr.services["gw"].out_degree == 2
    assert fr.services["gw"].error_rate == pytest.approx(0.01)
    assert fr.services["auth"].error_rate == 0.0


def test_prom_station_cpu_and_sleep_decomposition(prom_fit):
    _, fr = prom_fit
    # cpu_seconds / incoming is the station cpu_time exactly (2ms)
    assert fr.cpu_time_s == pytest.approx(2e-3)
    # gw: 10ms sojourn - 2 x (3ms auth sojourn + ~0.5ms wire) - 2ms
    # station ~ 1ms of scripted sleep
    assert fr.services["gw"].sleep_s == pytest.approx(1e-3, rel=0.05)
    assert fr.services["auth"].sleep_s == pytest.approx(1e-3, rel=0.05)
    # no occupancy data: the sojourn fallback is flagged, not silent
    assert any("sojourn" in f for f in fr.services["gw"].flags)


def test_prom_topology_decodes_and_sizes(prom_fit):
    _, fr = prom_fit
    doc = fr.topology_doc
    assert doc["defaults"]["responseSize"] == 128
    by_name = {s["name"]: s for s in doc["services"]}
    assert by_name["gw"]["isEntrypoint"] is True
    calls = [c for c in by_name["gw"]["script"]
             if isinstance(c, dict) and "call" in c]
    assert len(calls) == 2
    # the doc must survive the real decoder (fit already gates on it)
    ServiceGraph.decode(copy.deepcopy(doc))


def test_prom_qps_from_totals_over_duration(prom_fit):
    _, fr = prom_fit
    assert fr.qps_mean == pytest.approx(100.0)  # 6000 entry req / 60s
    assert any("flat schedule" in n for n in fr.notes)


# -- envoy reader ------------------------------------------------------


@pytest.fixture(scope="module")
def envoy_fit():
    obs = read_path(str(DATA / "envoy_stats.json"))
    return obs, fit(obs, FitOptions(label="envoy", duration_s=60.0))


def test_envoy_coverage_counts_entries(envoy_fit):
    obs, _ = envoy_fit
    (cov,) = obs.inputs
    _coverage_partitions(cov)
    assert cov.format == "envoy"
    assert cov.lines_total == 9     # stats entries, not physical lines
    assert cov.lines_parsed == 8
    assert cov.lines_malformed == 1  # {"bad": "entry"}
    assert cov.samples_used == 6
    assert cov.samples_ignored == 2  # server.uptime, membership_healthy


def test_envoy_edges_errors_and_replicas(envoy_fit):
    _, fr = envoy_fit
    assert fr.entry == "frontend"   # ingress is a client alias
    assert fr.services["frontend"].error_rate == pytest.approx(0.02)
    assert fr.edges[("frontend", "backend")] == pytest.approx(2.0)
    assert fr.services["frontend"].replicas == 4  # upstream_cx_active
    # rq_time means (8ms / 2ms) land as sojourns; frontend's sleep is
    # the 8 - 2 x (2 + 0.5) - cpu_time residual ~ 2.9ms
    assert fr.services["frontend"].sleep_s == pytest.approx(
        3e-3 - DEFAULT_CPU_TIME_S, rel=0.05
    )
    assert any("no timestamped windows" in n.lower() for n in fr.notes)


# -- csv trace reader --------------------------------------------------


@pytest.fixture(scope="module")
def csv_fit():
    obs = read_path(str(DATA / "trace.csv"))
    return obs, fit(obs, FitOptions(label="csv"))


def test_csv_coverage_partitions_every_line(csv_fit):
    obs, _ = csv_fit
    (cov,) = obs.inputs
    _coverage_partitions(cov)
    assert cov.lines_total == 124
    assert cov.lines_parsed == 120   # 40 traces x 3 spans
    assert cov.lines_comment == 2    # header + comment row
    assert cov.lines_malformed == 1  # timestamp "notatime"
    assert cov.lines_blank == 1
    assert "notatime" in cov.malformed_examples[0][1]


def test_csv_self_time_is_concurrency_safe(csv_fit):
    _, fr = csv_fit
    # api: rt 50ms minus the UNION of the two overlapping 20ms child
    # spans = 30ms (subtracting both would give 10ms)
    api = fr.services["api"]
    assert api.self_time_s == pytest.approx(30e-3, rel=0.01)
    assert api.concurrent is True
    assert api.self_hist, "log-bucket histogram recorded"
    # leaves measure their own rt as self-time
    assert fr.services["db"].self_time_s == pytest.approx(20e-3)


def test_csv_concurrent_group_in_emitted_script(csv_fit):
    _, fr = csv_fit
    by_name = {s["name"]: s for s in fr.topology_doc["services"]}
    groups = [c for c in by_name["api"]["script"] if isinstance(c, list)]
    assert len(groups) == 1 and len(groups[0]) == 2
    assert {c["call"] if isinstance(c["call"], str) else
            c["call"]["service"] for c in groups[0]} == {"db", "cache"}


def test_csv_errors_and_qps_schedule(csv_fit):
    _, fr = csv_fit
    assert fr.services["db"].error_rate == pytest.approx(2 / 40)
    assert fr.qps_schedule == pytest.approx([10.0] * 4)
    assert fr.qps_mean == pytest.approx(10.0)
    assert fr.window_s == 1.0


# -- dropped-with-reason accounting ------------------------------------


def test_cycle_and_unreachable_drop_with_reasons():
    text = "\n".join([
        'service_outgoing_requests_total{service="client",'
        'destination_service="a"} 100',
        'service_outgoing_requests_total{service="a",'
        'destination_service="b"} 100',
        'service_outgoing_requests_total{service="b",'
        'destination_service="a"} 100',
        'service_incoming_requests_total{service="a"} 200',
        'service_incoming_requests_total{service="b"} 100',
        'service_incoming_requests_total{service="orphan"} 50',
    ]) + "\n"
    fr = fit(read_prometheus(text), FitOptions(duration_s=10.0))
    assert set(fr.services) == {"a", "b"}
    reasons = {tuple(d["edge"]): d["reason"]
               for d in fr.dropped["edges"]}
    assert "cycle" in reasons[("b", "a")]
    svc_reasons = {d["service"]: d["reason"]
                   for d in fr.dropped["services"]}
    assert "unreachable" in svc_reasons["orphan"]


def test_empty_lead_and_tail_windows_dropped_accountably():
    from isotope_tpu.ingest import Observation

    obs = Observation()
    obs.svc("a").incoming = 30.0
    obs.add_edge("client", "a", 30.0)
    obs.clients_seen.add("client")
    obs.client_windows = [0.0, 0.0, 10.0, 10.0, 10.0, 0.0]
    obs.window_s = 1.0
    fr = fit(obs, FitOptions())
    assert fr.qps_schedule == pytest.approx([10.0] * 3)
    idxs = {d["index"] for d in fr.dropped["windows"]}
    assert idxs == {0, 1, 5}


# -- isotope-ingest/v1 artifact ----------------------------------------


def test_artifact_round_trip_and_invariants(tmp_path, prom_fit):
    obs, fr = prom_fit
    doc = report_mod.to_doc(fr, obs)
    check_doc(doc)
    path = tmp_path / "prom.ingest.json"
    report_mod.save_doc(doc, str(path))
    loaded = load_doc(str(path))
    assert loaded["schema"] == "isotope-ingest/v1"
    assert loaded["fit"]["degree_sequence"] == [2, 0]
    assert loaded == json.loads(json.dumps(doc))  # JSON-stable

    # a broken partition must fail the round-trip guard
    bad = copy.deepcopy(doc)
    bad["inputs"][0]["lines_parsed"] += 1
    with pytest.raises(ValueError, match="accounting"):
        check_doc(bad)


def test_format_report_renders(prom_fit):
    obs, fr = prom_fit
    doc = report_mod.to_doc(fr, obs)
    text = format_report(doc)
    assert "ingest 'prom'" in text
    assert "sample.prom" in text
    assert "1 malformed" in text
    assert "gw" in text


def test_closure_tolerances_pinned():
    # the documented contract (README "Trace-driven ingest"): loosening
    # a band is an API change, not a tweak
    assert CLOSURE_TOLERANCES == {
        "error_share_abs": 0.02,
        "self_time_mean_rel": 0.15,
        "self_time_each_rel": 0.35,
        "self_time_min_samples": 30,
        "self_time_band_share": 0.90,
        "degree_sequence": "exact",
        "qps_mean_rel": 0.10,
        "qps_window_rel": 0.25,
        "qps_window_share": 0.80,
    }


# -- ingest lint rules -------------------------------------------------


def test_lint_ingest_t027_saturating_schedule(prom_fit):
    obs, fr = prom_fit
    doc = report_mod.to_doc(fr, obs)
    # fitted station mu = 1/2ms = 500 hz; auth sees 2 visits/request,
    # so a 1000-qps window peak exceeds its 250-qps capacity
    hot = copy.deepcopy(doc)
    hot["fit"]["qps_schedule"] = [1000.0]
    findings = lint_ingest(fr.graph, hot)
    assert any(f.rule == "VET-T027" for f in findings)
    # the real 100-qps fit is quiet on T027 only if under capacity:
    # gw at 100 qps x 1 visit vs 500 hz station is fine, auth at
    # 2 visits vs 250 capacity is fine too
    assert not [f for f in lint_ingest(fr.graph, doc)
                if f.rule == "VET-T027"]


def test_lint_ingest_t028_degenerate_service(prom_fit):
    obs, fr = prom_fit
    doc = report_mod.to_doc(fr, obs)
    degenerate = copy.deepcopy(doc)
    degenerate["fit"]["services"][0]["observed"]["samples"] = 0.0
    findings = lint_ingest(fr.graph, degenerate)
    assert any(f.rule == "VET-T028" for f in findings)
    assert not [f for f in lint_ingest(fr.graph, doc)
                if f.rule == "VET-T028"]


def test_ingest_rules_registered():
    from isotope_tpu.analysis.findings import RULES

    assert "VET-T027" in RULES and "VET-T028" in RULES


# -- merged multi-input observation ------------------------------------


def test_inputs_merge_into_one_observation():
    obs = read_path(str(DATA / "sample.prom"))
    obs = read_path(str(DATA / "envoy_stats.json"), obs=obs)
    assert len(obs.inputs) == 2
    assert {c.format for c in obs.inputs} == {"prometheus", "envoy"}
    # both meshes land in one IR; the fit keeps whatever the chosen
    # entrypoint reaches and drops the rest WITH reasons
    fr = fit(obs, FitOptions(entry="gw", duration_s=60.0))
    dropped = {d["service"] for d in fr.dropped["services"]}
    assert {"frontend", "backend"} <= dropped
    assert all(d["reason"] for d in fr.dropped["services"])


# -- self-closure on a live simulation ---------------------------------


@pytest.fixture(scope="module")
def closure_loop(tmp_path_factory):
    import jax

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.metrics import timeline as timeline_mod
    from isotope_tpu.metrics.prometheus import MetricsCollector
    from isotope_tpu.sim import LoadModel, SimParams, Simulator

    topo = {
        "defaults": {"requestSize": 128, "responseSize": 128},
        "services": [
            {"name": "gw", "isEntrypoint": True,
             "script": [{"sleep": "2ms"}, {"call": "auth"},
                        {"call": "cart"}]},
            {"name": "auth", "errorRate": "2%",
             "script": [{"sleep": "1ms"}]},
            {"name": "cart", "script": [{"sleep": "3ms"}]},
        ],
    }
    graph = ServiceGraph.decode(topo)
    compiled = compile_graph(graph)
    params = SimParams(timeline=True, timeline_window_s=1.0)
    sim = Simulator(compiled, params)
    collector = MetricsCollector(compiled)
    qps = 200.0
    summary, tl = sim.run_timeline(
        LoadModel(kind="open", qps=qps), 3000, jax.random.PRNGKey(0),
        collector=collector, window_s=1.0,
    )
    td = tmp_path_factory.mktemp("closure")
    (td / "full.prom").write_text(collector.full_text(summary))
    (td / "timeline.prom").write_text(
        timeline_mod.prometheus_text(compiled, tl)
    )
    obs = read_path(str(td / "full.prom"))
    obs = read_path(str(td / "timeline.prom"), obs=obs)
    fr = fit(obs, FitOptions(label="closure"))
    return graph, params, qps, obs, fr


def test_self_closure_within_tolerances(closure_loop):
    graph, params, qps, obs, fr = closure_loop
    closure = closure_check(graph, params.cpu_time_s, [qps], fr)
    detail = json.dumps(closure["checks"], indent=1)
    assert closure["ok"], detail
    by_name = {c["check"]: c for c in closure["checks"]}
    assert by_name["degree_sequence"]["fitted"] == [2, 0, 0]
    assert by_name["error_share"]["worst_abs_error"] <= 0.02
    assert by_name["qps_schedule"]["mean_rel_error"] <= 0.10


def test_self_closure_nothing_dropped(closure_loop):
    _, _, _, obs, fr = closure_loop
    for cov in obs.inputs:
        _coverage_partitions(cov)
    assert not fr.dropped["services"]
    assert not fr.dropped["edges"]


def test_self_closure_artifact_and_toml(closure_loop, tmp_path):
    from isotope_tpu.runner.config import load_toml

    graph, params, qps, obs, fr = closure_loop
    doc = report_mod.to_doc(fr, obs)
    doc["closure"] = closure_check(graph, params.cpu_time_s, [qps], fr)
    path = tmp_path / "closure.ingest.json"
    report_mod.save_doc(doc, str(path))
    rendered = format_report(load_doc(str(path)))
    assert "self-closure: PASS" in rendered

    (tmp_path / "closure.yaml").write_text(fr.graph.to_yaml())
    (tmp_path / "closure.toml").write_text(fr.toml_text)
    cfg = load_toml(tmp_path / "closure.toml")
    assert cfg.ingest and cfg.ingest["label"] == "closure"
    assert cfg.qps[0] == pytest.approx(fr.qps_mean, rel=1e-4)
    assert cfg.load_kind == "open"
    assert cfg.timeline is True
    # vet must be clean on the reconstruction
    findings = lint_graph(fr.graph, entry=fr.entry)
    findings += lint_ingest(fr.graph, doc)
    assert not [f for f in findings
                if f.rule in ("VET-T027", "VET-T028")], findings


# -- CLI -----------------------------------------------------------------


def test_run_ingest_cli_writes_artifacts(tmp_path, capsys):
    import argparse

    from isotope_tpu.commands.ingest_cmd import run_ingest

    args = argparse.Namespace(
        inputs=[str(DATA / "sample.prom")], format="auto",
        label="promcli", out_dir=str(tmp_path), entry=None,
        duration="60s", window="1s", cpu_time=None,
        connections=64, seed=0, json=False,
    )
    assert run_ingest(args) == 0
    out = capsys.readouterr().out
    assert "ingest 'promcli'" in out
    topo = ServiceGraph.from_yaml_file(str(tmp_path / "promcli.yaml"))
    assert {s.name for s in topo.services} == {"gw", "auth"}
    doc = load_doc(str(tmp_path / "promcli.ingest.json"))
    check_doc(doc)
    assert doc["fit"]["qps_mean"] == pytest.approx(100.0)
    assert (tmp_path / "promcli.toml").exists()
