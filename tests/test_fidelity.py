"""The real-Fortio ground-truth diff tool (isotope-tpu fidelity).

The vendored artifact ``tests/data/fortio_canonical_sample.json`` is a
stand-in ground truth: a full ``fortio load -json``-schema result for
the canonical topology (closed loop, 16 workers, 1000 qps, ~240 s),
generated once from the engine under a DIFFERENT seed and frozen.  The
tool must ingest the artifact schema (the one
perf/benchmark/runner/fortio.py:38-75 flattens), reconstruct the load,
and report per-percentile deltas — passing on matching ground truth
and failing on perturbed ground truth.  When real cluster artifacts
exist, the same command is the evidence path for the north star's
"p99 within 5%" clause.
"""
import copy
import json
import pathlib

import pytest

from isotope_tpu.metrics.fidelity import check_fidelity, load_from_artifact

DATA = pathlib.Path(__file__).parent / "data"
TOPO = (
    pathlib.Path(__file__).parent.parent
    / "examples/topologies/canonical.yaml"
)


@pytest.fixture(scope="module")
def artifact():
    with open(DATA / "fortio_canonical_sample.json") as f:
        return json.load(f)


def test_load_reconstruction(artifact):
    load, duration_s = load_from_artifact(artifact)
    assert load.kind == "closed"
    assert load.connections == 16
    assert load.qps == pytest.approx(1000.0)
    assert duration_s == pytest.approx(262.1, rel=0.01)


def test_load_reconstruction_qps_max(artifact):
    doc = dict(artifact, RequestedQPS="max")
    load, _ = load_from_artifact(doc)
    assert load.kind == "closed" and load.qps is None
    assert load.connections == 16


def test_fidelity_passes_on_matching_ground_truth(artifact):
    report = check_fidelity(
        artifact, TOPO.read_text(), tolerance=0.05,
        max_requests=240_000, seed=7,
    )
    assert report.deltas, "artifact percentiles must be compared"
    assert {d.percentile for d in report.deltas} == {
        50, 75, 90, 99, 99.9,
    }
    for d in report.deltas:
        assert abs(d.rel_err) <= 0.05, (
            f"p{d.percentile}: {d.rel_err:+.2%}"
        )
    assert report.ok
    assert report.actual_qps_sim == pytest.approx(
        report.actual_qps_fortio, rel=0.05
    )
    # the human-readable report renders one line per percentile + 2
    assert len(report.lines()) == len(report.deltas) + 3


def test_fidelity_fails_on_perturbed_ground_truth(artifact):
    doc = copy.deepcopy(artifact)
    for p in doc["DurationHistogram"]["Percentiles"]:
        if p["Percentile"] == 99:
            p["Value"] *= 1.25
    report = check_fidelity(
        doc, TOPO.read_text(), tolerance=0.05,
        max_requests=240_000, seed=7,
    )
    assert not report.ok
    bad = [d for d in report.deltas if d.percentile == 99][0]
    assert bad.rel_err < -0.05


def test_cli_subcommand_registered():
    from isotope_tpu.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["fidelity", "--help"])
    assert exc.value.code == 0
