"""Critical-path bucket scheduling (compiler/buckets.plan_segments).

The executor's segments run strictly sequentially, so the schedule's
critical path is the sum of per-segment costs (dispatch overhead +
padded elements — ``segment_cp_cost``, the same function the vet cost
model reports).  These tests pin that the default ``critical-path``
schedule is OPTIMAL over the partition space (brute-force enumeration
on small runs), never worse than the historical greedy, and that vet
surfaces the chosen schedule ranked by cost.
"""
import itertools

import numpy as np

from isotope_tpu.compiler.buckets import (
    MIN_SCAN_LEVELS,
    LevelShape,
    ScanBucketPlan,
    UnrolledLevelPlan,
    _bounds,
    _bucket_cost,
    _real_cost,
    plan_cp_cost,
    plan_segments,
    schedule_table,
    segment_cp_cost,
)


def _shape(size, pmax=1, children=1, calls=1, attempts=1, sparse=False,
           offset=0):
    return LevelShape(size=size, pmax=pmax, children=children,
                      calls=calls, attempts=attempts, sparse=sparse,
                      offset=offset)


def _chain_shapes(sizes):
    """A chain whose level d spawns exactly level d+1."""
    allsz = list(sizes) + [1]
    shapes = [
        _shape(s, children=allsz[i + 1], calls=allsz[i + 1])
        for i, s in enumerate(sizes)
    ]
    shapes.append(_shape(allsz[-1], calls=0, children=0))
    return shapes


def _spans(segs):
    return [
        (s.d0, s.d1) if isinstance(s, ScanBucketPlan) else s.d
        for s in segs
    ]


def _brute_force_best(shapes, i, j, waste):
    """Optimal partition cost of run [i..j] by full enumeration."""
    n = len(shapes)

    def feasible_bucket(a, b):
        run = shapes[a:b + 1]
        child = shapes[b + 1].size if b + 1 < n else 0
        return _bucket_cost(run, _bounds(run, child)) <= (
            waste * _real_cost(run)
        )

    best = None
    length = j - i + 1
    for cuts in itertools.product([0, 1], repeat=length - 1):
        # cut after position k when cuts[k] == 1
        parts = []
        start = i
        for k, c in enumerate(cuts):
            if c:
                parts.append((start, i + k))
                start = i + k + 1
        parts.append((start, j))
        segs = []
        ok = True
        for a, b in parts:
            if b - a + 1 >= MIN_SCAN_LEVELS:
                if not feasible_bucket(a, b):
                    ok = False
                    break
                run = shapes[a:b + 1]
                child = shapes[b + 1].size if b + 1 < n else 0
                bb, p, k_, at = _bounds(run, child)
                segs.append(ScanBucketPlan(a, b, bb, p, k_, at))
            else:
                segs.append(UnrolledLevelPlan(a))
        if not ok:
            continue
        cost = sum(segment_cp_cost(shapes, s) for s in segs)
        if best is None or cost < best:
            best = cost
    return best


def test_dp_is_optimal_against_brute_force():
    rng = np.random.default_rng(7)
    for _ in range(40):
        sizes = rng.integers(1, 40, int(rng.integers(3, 7))).tolist()
        waste = float(rng.uniform(1.2, 3.0))
        shapes = _chain_shapes(sizes)
        segs = plan_segments(shapes, waste=waste,
                             schedule="critical-path")
        run_segs = [
            s for s in segs
            if not (isinstance(s, UnrolledLevelPlan)
                    and shapes[s.d].leaf)
        ]
        got = sum(segment_cp_cost(shapes, s) for s in run_segs)
        want = _brute_force_best(shapes, 0, len(sizes) - 1, waste)
        assert got == want, (sizes, waste, _spans(segs))


def test_dp_never_worse_than_greedy_and_beats_it_when_skewed():
    # greedy's left-maximal extension strands level 3 outside a bucket
    # on this skew; the DP folds the whole run into ONE scan body
    shapes = _chain_shapes([37, 8, 5, 6, 29, 38])
    waste = 2.942
    greedy = plan_segments(shapes, waste=waste, schedule="greedy")
    dp = plan_segments(shapes, waste=waste, schedule="critical-path")
    assert plan_cp_cost(shapes, dp) < plan_cp_cost(shapes, greedy)
    assert _spans(greedy)[:2] == [0, (1, 5)]
    assert _spans(dp)[0] == (0, 5)

    rng = np.random.default_rng(1)
    for _ in range(60):
        sizes = rng.integers(1, 40, int(rng.integers(3, 8))).tolist()
        waste = float(rng.uniform(1.1, 3.5))
        shapes = _chain_shapes(sizes)
        g = plan_segments(shapes, waste=waste, schedule="greedy")
        c = plan_segments(shapes, waste=waste,
                          schedule="critical-path")
        assert plan_cp_cost(shapes, c) <= plan_cp_cost(shapes, g)


def test_waste_budget_stays_hard_under_dp():
    # geometric growth at a tight budget: no feasible bucket exists,
    # the DP must unroll everything (the historical pin)
    shapes = [
        _shape(3 ** i, children=3 ** (i + 1), calls=3 ** (i + 1))
        for i in range(4)
    ] + [_shape(81, calls=0, children=0)]
    segs = plan_segments(shapes, waste=1.2, schedule="critical-path")
    assert all(isinstance(s, UnrolledLevelPlan) for s in segs)


def test_schedule_table_ranked_by_cost():
    shapes = _chain_shapes([4, 4, 4, 4])
    segs = plan_segments(shapes, waste=4.0)
    rows = schedule_table(shapes, segs)
    costs = [r["cp_cost_elems"] for r in rows]
    assert costs == sorted(costs, reverse=True)
    assert abs(sum(r["cp_share"] for r in rows) - 1.0) < 1e-9
    assert {r["position"] for r in rows} == set(range(len(segs)))
    kinds = {r["kind"] for r in rows}
    assert kinds <= {"scan", "unrolled", "leaf", "sparse", "tiled"}


def test_simulator_threads_schedule_param():
    import jax

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, SimParams, Simulator

    chain = (
        "services:\n- name: s0\n  isEntrypoint: true\n"
        "  script:\n  - call: s1\n"
    )
    for i in range(1, 6):
        chain += f"- name: s{i}\n"
        if i < 5:
            chain += f"  script:\n  - call: s{i + 1}\n"
    g = ServiceGraph.from_yaml(chain)
    cp = Simulator(compile_graph(g), SimParams())
    gr = Simulator(
        compile_graph(g), SimParams(bucket_schedule="greedy")
    )
    assert cp.params.bucket_schedule == "critical-path"
    # uniform chain: both schedules converge on one bucket, and the
    # results stay bit-identical across plans (the executor contract)
    r1 = cp.run(LoadModel(kind="open", qps=200.0), 256,
                jax.random.PRNGKey(0))
    r2 = gr.run(LoadModel(kind="open", qps=200.0), 256,
                jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(r1.client_latency), np.asarray(r2.client_latency),
        rtol=3e-7,
    )


def test_bad_schedule_param_rejected():
    import pytest

    from isotope_tpu.sim import SimParams

    with pytest.raises(ValueError):
        SimParams(bucket_schedule="alphabetical")


def test_vet_surfaces_bucket_schedule_and_residual_rule():
    from isotope_tpu.analysis import vet_simulator
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.graph import ServiceGraph
    from isotope_tpu.sim import LoadModel, SimParams, Simulator

    skewed = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: hub}, {call: s0}, {call: s1}]
- name: hub
  script:
  - sleep: 1ms
  - call: w0
  - sleep: 1ms
  - call: w1
  - sleep: 1ms
  - call: w2
- name: s0
- name: s1
- name: w0
- name: w1
- name: w2
"""
    g = ServiceGraph.from_yaml(skewed)
    params = SimParams(sparse_level_elems=1, sparse_tile_pmax=2)
    sim = Simulator(compile_graph(g), params)
    assert any(
        lvl.tiled is not None and lvl.tiled.residual is not None
        for lvl in sim._levels
    )
    report = vet_simulator(
        sim, LoadModel(kind="open", qps=100.0), graph=g,
        trace=False,
    )
    rows = report.meta.get("bucket_schedule")
    assert rows and any(r["kind"] == "tiled" for r in rows)
    costs = [r["cp_cost_elems"] for r in rows]
    assert costs == sorted(costs, reverse=True)
    residual_findings = [
        f for f in report.findings if f.rule == "VET-C006"
    ]
    assert residual_findings, "VET-C006 did not fire on the residual"
    assert "sparse" in residual_findings[0].message
    # a fully-dense topology reports no VET-C006
    clean = Simulator(compile_graph(g), SimParams())
    rep2 = vet_simulator(
        clean, LoadModel(kind="open", qps=100.0), graph=g,
        trace=False,
    )
    assert not [f for f in rep2.findings if f.rule == "VET-C006"]
