"""Driver entry-point smoke tests (virtual 8-device CPU mesh)."""
import sys
import pathlib

import jax
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

import __graft_entry__  # noqa: E402


def test_entry_jits_single_device():
    fn, args = __graft_entry__.entry()
    res = jax.jit(fn)(*args)
    # 2048 requests x 121 hops, all always sent
    assert int(res.hop_events) == 2048 * 121


@pytest.mark.slow
@pytest.mark.slow
def test_dryrun_multichip_8():
    __graft_entry__.dryrun_multichip(8)
