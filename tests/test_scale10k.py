"""BASELINE configs[3]: the 10,000-service realistic path compiles and
runs (CPU-sized request counts; the TPU rate is measured by bench.py)."""
import jax
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.generators import (
    realistic_topology,
    with_call_policy,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator


@pytest.fixture(scope="module")
def compiled10k():
    doc = realistic_topology(10_000, archetype="multitier", seed=0)
    return compile_graph(ServiceGraph.decode(doc))


def test_10k_compile_shape(compiled10k):
    # BA(m=1) graphs are trees: one hop per service, no unroll blowup
    assert compiled10k.num_services == 10_000
    assert compiled10k.num_hops == 10_000
    assert len(compiled10k.levels) < 40


def test_10k_simulates_through_scan_path(compiled10k):
    sim = Simulator(compiled10k)
    s = sim.run_summary(
        LoadModel(kind="open", qps=1000.0), 64, jax.random.PRNGKey(0),
        block_size=32,
    )
    assert float(s.count) == 64
    # every request traverses the whole tree (no probability/errors)
    assert float(s.hop_events) == 64 * 10_000
    # deep sequential scripts: one request sweeps all 10k services, so
    # client latency is thousands of network+service legs
    assert 1.0 < s.mean_latency_s < 30.0
    assert not bool(s.unstable.any())


@pytest.mark.slow
@pytest.mark.slow
def test_star10k_with_timeouts_keeps_sparse_encoding():
    # BASELINE configs[3] names retries/timeouts on the 10k graph; the
    # star archetype's skewed hub level is exactly where the non-dense
    # step encodings matter (a dense grid block-starves it), and until
    # r5 finite timeouts forced the dense fallback.  Since PR 6 the
    # level lowers to the DENSE-BLOCKED tiling: the thousands of
    # narrow spokes run as dense tiles while the ~2,000-step hubs keep
    # the true sparse call-slot encoding as the residual — and the
    # level still carries the finite timeouts.
    doc = with_call_policy(
        realistic_topology(10_000, archetype="star", seed=0),
        timeout="30s",
    )
    sim = Simulator(compile_graph(ServiceGraph.decode(doc)))
    tiled_lvls = [
        lvl for lvl in sim._levels if lvl.tiled is not None
    ]
    assert tiled_lvls, "the star hub level must tile"
    assert any(
        lvl.tiled.residual is not None for lvl in tiled_lvls
    ), "the wide hubs must keep the sparse residual"
    assert any(lvl.finite_timeout for lvl in tiled_lvls), (
        "the tiled level itself carries the finite timeouts"
    )
    # tiling off restores the pure sparse encoding (the pre-PR 6 pin)
    sim_sp = Simulator(
        compile_graph(ServiceGraph.decode(doc)),
        SimParams(sparse_tiling=False),
    )
    assert any(lvl.sparse is not None for lvl in sim_sp._levels)


@pytest.mark.slow
@pytest.mark.slow
def test_100k_generates_and_compiles_host_side():
    # BASELINE configs[4]: generation is O(n log n) (Fenwick sampler)
    # and the BFS unroll stays linear; the on-chip run is validated on
    # TPU (README "Scale") — jit at this size is too slow for CI
    doc = realistic_topology(100_000, archetype="multitier", seed=0)
    compiled = compile_graph(ServiceGraph.decode(doc))
    assert compiled.num_services == 100_000
    assert compiled.num_hops == 100_000
    assert len(compiled.levels) < 50
