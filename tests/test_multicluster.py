"""Multicluster topology split: per-service cluster placement and the
cross-cluster network edge class.

The reference splits one service graph across cluster1/cluster2 (+ VM
workloads) so cross-cluster calls traverse egress/ingress gateways
(perf/load/templates/service-graph.gen.yaml:1-3, common.sh:36-42).
Here placement is a topology field (``cluster:``) and cross-cluster
edges pay ``NetworkModel.cross_cluster_latency_s`` /
``cross_cluster_bytes_per_second`` — in the engine, the feedback
solver, AND the DES oracle (per-call edge classes), which pins the two
implementations against each other exactly under deterministic times.
"""
import dataclasses
import pathlib

import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.compiler.program import hop_wire_times
from isotope_tpu.convert import graphviz as graphviz_mod
from isotope_tpu.convert import kubernetes as k8s_mod
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import NetworkModel
from isotope_tpu.sim.oracle import OracleSimulator

EXAMPLE = (
    pathlib.Path(__file__).parent.parent
    / "examples/topologies/two-cluster-canonical.yaml"
)

TWO_CLUSTER_CHAIN = """
services:
- name: a
  isEntrypoint: true
  cluster: cluster1
  script: [{call: b}]
- name: b
  cluster: cluster2
  script: [{call: c}]
- name: c
  cluster: cluster2
"""

QUIET = LoadModel(kind="open", qps=0.001, duration_s=1.0)
DET = SimParams(service_time="deterministic")


def test_cluster_field_round_trips():
    g = ServiceGraph.from_yaml(TWO_CLUSTER_CHAIN)
    assert [s.cluster for s in g.services] == [
        "cluster1", "cluster2", "cluster2"
    ]
    g2 = ServiceGraph.from_yaml(g.to_yaml())
    assert [s.cluster for s in g2.services] == [
        "cluster1", "cluster2", "cluster2"
    ]


def test_cluster_defaults_block_inheritance():
    g = ServiceGraph.from_yaml_file(str(EXAMPLE))
    by_name = {s.name: s.cluster for s in g.services}
    assert by_name == {
        "a": "cluster2", "b": "cluster2",
        "c": "cluster1", "d": "cluster1",
    }
    # round-trip preserves both the defaults block and the overrides
    g2 = ServiceGraph.from_yaml(g.to_yaml())
    assert {s.name: s.cluster for s in g2.services} == by_name


def test_cluster_must_be_string():
    with pytest.raises(ValueError, match="cluster must be a string"):
        ServiceGraph.from_yaml(
            "services:\n- name: a\n  isEntrypoint: true\n  cluster: 3\n"
        )


def test_compile_carries_cluster_ids():
    c = compile_graph(ServiceGraph.from_yaml(TWO_CLUSTER_CHAIN))
    assert c.services.cluster_names == ("cluster1", "cluster2")
    np.testing.assert_array_equal(c.services.cluster, [0, 1, 1])
    # single-cluster topologies stay degenerate (zero ids, no cross)
    c1 = compile_graph(
        ServiceGraph.from_yaml("services:\n- name: a\n  isEntrypoint: true\n")
    )
    assert c1.services.num_clusters == 1


def test_cross_cluster_wire_times():
    c = compile_graph(ServiceGraph.from_yaml(TWO_CLUSTER_CHAIN))
    net = NetworkModel(
        base_latency_s=100e-6,
        cross_cluster_latency_s=2e-3,
        cross_cluster_bytes_per_second=1.25e8,
    )
    out, back = hop_wire_times(c, net)
    # hop 0: client -> a (co-located, intra); hop 1: a -> b (cross);
    # hop 2: b -> c (intra: both cluster2)
    assert out[0] == pytest.approx(100e-6)
    assert out[1] == pytest.approx(100e-6 + 2e-3)
    assert out[2] == pytest.approx(100e-6)
    assert back[1] == pytest.approx(100e-6 + 2e-3)


def test_cross_cluster_hops_cost_more_end_to_end():
    # the capability VERDICT r3 asked for: cross-cluster hops observably
    # cost more in a canonical two-cluster example
    params = dataclasses.replace(
        DET,
        network=NetworkModel(cross_cluster_latency_s=5e-3),
    )
    split = Simulator(
        compile_graph(ServiceGraph.from_yaml(TWO_CLUSTER_CHAIN)), params
    ).run(QUIET, 16, jax.random.PRNGKey(0))
    flat_yaml = TWO_CLUSTER_CHAIN.replace("cluster2", "cluster1")
    flat = Simulator(
        compile_graph(ServiceGraph.from_yaml(flat_yaml)), params
    ).run(QUIET, 16, jax.random.PRNGKey(0))
    delta = float(split.client_latency[0] - flat.client_latency[0])
    # exactly one cross edge (a->b), two legs, 5 ms each
    assert delta == pytest.approx(2 * 5e-3, rel=1e-4)


def test_oracle_engine_parity_two_cluster():
    # deterministic quiet-load parity pins the engine's cluster-aware
    # wire times against the DES oracle's per-call edge classes
    params = dataclasses.replace(
        DET,
        network=NetworkModel(
            cross_cluster_latency_s=3e-3,
            cross_cluster_bytes_per_second=1.25e7,
        ),
    )
    g = ServiceGraph.from_yaml_file(str(EXAMPLE))
    engine = Simulator(compile_graph(g), params)
    res_e = engine.run(QUIET, 32, jax.random.PRNGKey(0))
    oracle = OracleSimulator(g, params)
    res_o = oracle.run(QUIET, 32, seed=0)
    np.testing.assert_allclose(
        res_o.client_latency,
        np.asarray(res_e.client_latency, np.float64),
        rtol=1e-5,
    )


def test_graphviz_cluster_subgraphs():
    g = ServiceGraph.from_yaml(TWO_CLUSTER_CHAIN)
    dot = graphviz_mod.to_dot(g)
    assert 'subgraph "cluster_0"' in dot
    assert 'label="cluster1";' in dot
    assert 'label="cluster2";' in dot
    # single-cluster graphs keep the flat layout (golden-stable)
    flat = graphviz_mod.to_dot(
        ServiceGraph.from_yaml("services:\n- name: a\n  isEntrypoint: true\n")
    )
    assert "subgraph" not in flat


def test_kubernetes_cluster_filter():
    g = ServiceGraph.from_yaml(TWO_CLUSTER_CHAIN)
    topo = TWO_CLUSTER_CHAIN
    all_m = k8s_mod.service_graph_to_manifests(g, topo)
    names = [
        m["metadata"]["name"]
        for m in all_m
        if m["kind"] == "Deployment"
    ]
    assert set(names) >= {"a", "b", "c"}

    c1 = k8s_mod.service_graph_to_manifests(
        g, topo, k8s_mod.ConvertOptions(cluster="cluster1")
    )
    dep1 = [
        m["metadata"]["name"] for m in c1 if m["kind"] == "Deployment"
    ]
    # cluster1 holds the entrypoint: its Deployment + the load client
    assert "a" in dep1 and "b" not in dep1 and "c" not in dep1
    assert any("client" in n for n in dep1)
    # the ConfigMap always embeds the full topology
    cm = next(m for m in c1 if m["kind"] == "ConfigMap")
    assert "cluster2" in list(cm["data"].values())[0]

    c2 = k8s_mod.service_graph_to_manifests(
        g, topo, k8s_mod.ConvertOptions(cluster="cluster2")
    )
    dep2 = [
        m["metadata"]["name"] for m in c2 if m["kind"] == "Deployment"
    ]
    assert "b" in dep2 and "c" in dep2 and "a" not in dep2
    assert not any("client" in n for n in dep2)
