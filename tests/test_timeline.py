"""Simulation flight recorder (metrics/timeline.py).

Invariants pinned here:

- windowed series reconcile with the run-level aggregates: arrivals
  sum to the request count, per-window errors sum to the run error
  count, per-service arrivals sum to hop_events, per-window latency
  sums to the run latency sum;
- the per-(service, window) occupancy integrals match a brute-force
  interval-overlap computation on the same SimResults;
- ``SimParams.timeline=False`` leaves every RunSummary field
  byte-identical (and a timeline run's RunSummary matches the
  unrecorded run of the same arguments bit-for-bit);
- block-stacked accumulation equals single-block accumulation; the
  sharded psum merge is bit-equal to the emulated host merge;
- every summary leaf stays O(W) / O(S * W) — never O(N);
- the window planner clamps (widening windows) instead of OOMing;
- surfaces: timestamped Prometheus exposition (escaping, ordering,
  one sample per service x window, round-trip through query.py),
  per-window monitor rows next to legacy run-level rows, the convoy
  detector, the control-plane window projection, the report section,
  the vet cost-model accounting, and the bench regression gate.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics import timeline as tm
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator

KEY = jax.random.PRNGKey(0)
LOAD = LoadModel(kind="open", qps=200.0)

ERRCHAIN = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 5%
  script:
  - call: mid
- name: mid
  script:
  - call: leaf
- name: leaf
  script:
  - sleep: 1ms
"""


@pytest.fixture(scope="module")
def tree13():
    return compile_graph(
        ServiceGraph.from_yaml_file(
            "examples/topologies/tree-13-services.yaml"
        )
    )


@pytest.fixture(scope="module")
def tl_sim(tree13):
    return Simulator(
        tree13, SimParams(timeline=True, timeline_window_s=1.0)
    )


@pytest.fixture(scope="module")
def recorded(tl_sim):
    return tl_sim.run_timeline(LOAD, 1024, KEY, block_size=256)


# -- reconciliation ----------------------------------------------------------


def test_windowed_series_reconcile_with_run_aggregates(recorded):
    s, tl = recorded
    assert float(tl.count) == float(s.count)
    assert float(np.asarray(tl.arrivals).sum()) == float(s.count)
    assert float(np.asarray(tl.completions).sum()) == float(s.count)
    assert float(np.asarray(tl.errors).sum()) == float(s.error_count)
    assert float(np.asarray(tl.svc_arrivals).sum()) == float(
        s.hop_events
    )
    assert float(np.asarray(tl.latency_hist).sum()) == float(s.count)
    np.testing.assert_allclose(
        float(np.asarray(tl.latency_sum).sum()),
        float(s.latency_sum),
        rtol=1e-5,
    )


def test_error_windows_reconcile():
    compiled = compile_graph(
        ServiceGraph.decode(yaml.safe_load(ERRCHAIN))
    )
    sim = Simulator(
        compiled, SimParams(timeline=True, timeline_window_s=1.0)
    )
    s, tl = sim.run_timeline(LOAD, 2048, KEY, block_size=512)
    assert float(s.error_count) > 0
    assert float(np.asarray(tl.errors).sum()) == float(s.error_count)
    # per-service error windows sum to the entry's executed 500s
    assert float(np.asarray(tl.svc_errors).sum()) > 0


def test_occupancy_integral_matches_brute_force(tree13, tl_sim):
    res = tl_sim.run(LOAD, 512, KEY)
    spec = tm.build_spec(tree13, 4, 1.0)
    tl = tm.timeline_block(res, spec)
    sent = np.asarray(res.hop_sent)
    st = np.asarray(res.hop_start, np.float64)
    en = st + np.asarray(res.hop_latency, np.float64)
    hs = tree13.hop_service
    brute = np.zeros((tree13.num_services, 4))
    for w in range(4):
        lo, hi = w * 1.0, (w + 1) * 1.0
        ov = np.clip(
            np.minimum(en, hi) - np.maximum(st, lo), 0.0, None
        ) * sent
        for s in range(tree13.num_services):
            brute[s, w] = ov[:, hs == s].sum()
    np.testing.assert_allclose(
        np.asarray(tl.svc_inflight_s), brute, atol=2e-3, rtol=1e-3
    )
    # busy is the same family minus the queueing wait: bounded above
    # by in-flight everywhere
    assert (
        np.asarray(tl.svc_inflight_s) - np.asarray(tl.svc_busy_s)
        >= -1e-3
    ).all()


def test_queue_depth_appears_under_load(tree13):
    # near-saturation open loop: waits become nonzero, so the queued
    # integral (inflight - busy) must be visibly positive somewhere
    chain = compile_graph(ServiceGraph.decode(yaml.safe_load("""
services:
- name: a
  isEntrypoint: true
  script: [{call: b}]
- name: b
""")))
    sim = Simulator(
        chain, SimParams(timeline=True, timeline_window_s=0.5)
    )
    _, tl = sim.run_timeline(
        LoadModel(kind="open", qps=11_000.0), 4096, KEY,
        block_size=4096,
    )
    queue = (
        np.asarray(tl.svc_inflight_s) - np.asarray(tl.svc_busy_s)
    )
    assert queue.max() > 1e-4


# -- gating / byte-identity --------------------------------------------------


def test_off_leaves_run_summary_byte_identical(tree13, recorded):
    plain = Simulator(tree13)  # timeline defaults off
    s_off = plain.run_summary(LOAD, 1024, KEY, block_size=256)
    s_on, _ = recorded
    for name, a, b in zip(
        s_off._fields,
        s_off._replace(metrics=None),
        s_on._replace(metrics=None),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_run_timeline_requires_flag(tree13):
    sim = Simulator(tree13)
    with pytest.raises(ValueError, match="timeline=True"):
        sim.run_timeline(LOAD, 64, KEY)


def test_summary_stays_o_windows(tree13, recorded):
    n = 1024
    _, tl = recorded
    bound = tree13.num_services * tl.num_windows * 64
    for leaf in jax.tree.leaves(tl):
        assert np.asarray(leaf).size <= bound
        assert np.asarray(leaf).size < n * tree13.num_hops


# -- block / shard equivalence ----------------------------------------------


def test_blocked_accumulation_equals_single_block(tree13, tl_sim):
    res = tl_sim.run(LOAD, 512, KEY)
    spec = tm.build_spec(tree13, 4, 1.0)
    full = tm.timeline_block(res, spec)

    def part(sl):
        return res._replace(
            client_start=res.client_start[sl],
            client_latency=res.client_latency[sl],
            client_error=res.client_error[sl],
            hop_sent=res.hop_sent[sl],
            hop_error=res.hop_error[sl],
            hop_latency=res.hop_latency[sl],
            hop_start=res.hop_start[sl],
            hop_wait=res.hop_wait[sl],
        )

    a = tm.timeline_block(part(slice(None, 256)), spec)
    b = tm.timeline_block(part(slice(256, None)), spec)
    summed = jax.tree.map(
        lambda x, y: x + y,
        a._replace(window_s=jnp.float32(0.0)),
        b._replace(window_s=jnp.float32(0.0)),
    )
    for name, got, want in zip(
        full._fields, summed,
        full._replace(window_s=jnp.float32(0.0)),
    ):
        # the occupancy integrals are mathematically additive but
        # their F-difference form cancels differently per block in
        # f32 (~1e-4 s on ~0.3 s cells); counts stay exact
        occ = name in ("svc_inflight_s", "svc_busy_s")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want),
            rtol=2e-2 if occ else 2e-5,
            atol=1e-3 if occ else 1e-6,
            err_msg=name,
        )


@pytest.mark.slow
@pytest.mark.slow
def test_sharded_psum_equals_emulated(tree13):
    from isotope_tpu.parallel import ShardedSimulator, make_mesh

    sh = ShardedSimulator(
        tree13, make_mesh(4, 2),
        SimParams(timeline=True, timeline_window_s=1.0),
    )
    s1, t1 = sh.run_timeline(LOAD, 4096, KEY, block_size=512)
    s2, t2 = sh.run_timeline_emulated(LOAD, 4096, KEY, block_size=512)
    for name, x, y in zip(t1._fields, t1, t2):
        assert np.array_equal(np.asarray(x), np.asarray(y)), name
    assert float(t1.count) == 4096.0
    # the RunSummary halves agree too (same streams)
    assert np.array_equal(
        np.asarray(s1.latency_hist), np.asarray(s2.latency_hist)
    )


# -- window planner ----------------------------------------------------------


def test_plan_windows_clamps_with_warning():
    msgs = []
    w, dt, clamped = tm.plan_windows(
        1000.0, 1.0, max_windows=16, num_services=4, log=msgs.append
    )
    assert clamped and w == 16 and msgs
    # widened windows still cover the duration
    assert w * dt >= 1000.0
    # the element budget clamps too, independently of max_windows
    w2, dt2, clamped2 = tm.plan_windows(
        1000.0, 1.0, max_windows=1000, num_services=100_000,
        elem_budget=200_000, log=msgs.append,
    )
    assert clamped2 and w2 == 2 and w2 * dt2 >= 1000.0
    # no clamp: the asked-for grid survives
    w3, dt3, clamped3 = tm.plan_windows(10.0, 1.0, 256, 13)
    assert (w3, dt3, clamped3) == (10, 1.0, False)


def test_engine_clamps_window_count(tree13):
    sim = Simulator(
        tree13,
        SimParams(
            timeline=True, timeline_window_s=0.001,
            timeline_max_windows=8,
        ),
    )
    _, tl = sim.run_timeline(LOAD, 512, KEY, block_size=256)
    assert tl.num_windows == 8
    assert float(np.asarray(tl.arrivals).sum()) == 512.0


# -- convoy / control plane --------------------------------------------------


def test_convoy_detector_flags_correlated_series(tree13):
    # synthetic star: entry (service of hop 0) waits exactly when the
    # leaves are busy -> correlation ~ 1
    star = compile_graph(ServiceGraph.decode(yaml.safe_load("""
services:
- name: hub
  isEntrypoint: true
  script:
  - - call: s1
    - call: s2
- name: s1
- name: s2
""")))
    W = 8
    S = star.num_services
    rng = np.random.default_rng(0)
    leaf_busy = rng.uniform(0.1, 1.0, W)
    inflight = np.ones((S, W))
    busy = np.ones((S, W))
    entry = int(star.entry_service)
    busy[entry] = 1.0 - 0.8 * leaf_busy   # wait share tracks leaf busy
    for s in range(S):
        if s != entry:
            busy[s] = leaf_busy
            inflight[s] = leaf_busy
    tl = tm.TimelineSummary(
        window_s=np.float32(1.0),
        count=np.float32(100.0),
        arrivals=np.full(W, 10.0, np.float32),
        completions=np.full(W, 10.0, np.float32),
        errors=np.zeros(W, np.float32),
        latency_sum=np.zeros(W, np.float32),
        latency_hist=np.zeros((W, 64), np.float32),
        svc_arrivals=np.ones((S, W), np.float32),
        svc_completions=np.ones((S, W), np.float32),
        svc_errors=np.zeros((S, W), np.float32),
        svc_inflight_s=inflight.astype(np.float32),
        svc_busy_s=busy.astype(np.float32),
    )
    cv = tm.convoy(star, tl)
    assert cv["entry"] == "hub"
    assert cv["num_leaf_services"] == 2
    assert cv["correlation"] > 0.95
    assert cv["convoy_suspected"]
    # anti-correlated busy shares must NOT flag
    busy2 = busy.copy()
    busy2[entry] = 0.2 + 0.8 * leaf_busy
    cv2 = tm.convoy(star, tl._replace(svc_busy_s=busy2.astype(
        np.float32)))
    assert not cv2["convoy_suspected"]


def test_controlplane_windows_compose():
    from isotope_tpu.sim.controlplane import (
        PilotModel,
        push_convergence,
    )

    conv = push_convergence(PilotModel(), 10, 5, 40)
    series = conv.window_series(0.005, 16)
    assert series["proxies"] == 40
    assert sum(series["acks"]) == 40
    assert series["converged_fraction"][-1] == 1.0
    frac = series["converged_fraction"]
    assert all(a <= b + 1e-12 for a, b in zip(frac, frac[1:]))


# -- doc / report surfaces ---------------------------------------------------


def test_to_doc_shape_and_table(tree13, recorded):
    _, tl = recorded
    doc = tm.to_doc(tree13, tl)
    assert doc["schema"] == "isotope-timeline/v1"
    assert len(doc["windows"]) == tl.num_windows
    assert sum(w["arrivals"] for w in doc["windows"]) == float(
        tl.count
    )
    assert doc["services"]
    for svc in doc["services"].values():
        assert len(svc["utilization"]) == tl.num_windows
        assert all(v >= 0 for v in svc["queue_depth"])
    text = tm.format_table(doc)
    assert "timeline:" in text and "convoy" in text
    # controlplane overlay embeds verbatim
    doc2 = tm.to_doc(
        tree13, tl, controlplane={"proxies": 3, "acks": [3],
                                  "converged_fraction": [1.0],
                                  "converged_window": 0},
    )
    assert doc2["controlplane"]["proxies"] == 3


def test_report_renders_timeline_section(tmp_path, tree13, recorded):
    from isotope_tpu import report

    _, tl = recorded
    doc = tm.to_doc(tree13, tl)
    (tmp_path / "run1.timeline.json").write_text(json.dumps(doc))
    (tmp_path / "results.jsonl").write_text(json.dumps({
        "Labels": "run1_none_200qps_64c", "ActualQPS": 200.0,
        "NumThreads": 64, "p50": 1000.0, "p90": 1500.0,
        "p99": 2000.0, "errorPercent": 0.0,
    }) + "\n")
    out = tmp_path / "report.html"
    report.write_report(tmp_path, out)
    html_text = out.read_text()
    assert "Timelines" in html_text
    assert "spark" in html_text


def test_perfetto_timeline_counters(tmp_path, tree13, recorded):
    from isotope_tpu.metrics.export import write_timeline_perfetto

    _, tl = recorded
    path = tmp_path / "tl.perfetto.json"
    n = write_timeline_perfetto(path, tree13, tl)
    doc = json.loads(path.read_text())
    assert n == len(doc["traceEvents"]) > tl.num_windows
    kinds = {e["name"] for e in doc["traceEvents"]}
    assert "client qps" in kinds
    assert any(k.startswith("util ") for k in kinds)
    # counter events ride REAL sim time
    qps_ts = [
        e["ts"] for e in doc["traceEvents"] if e["name"] == "client qps"
    ]
    assert qps_ts == sorted(qps_ts)


# -- prometheus / query round-trip -------------------------------------------


def test_timestamped_exposition_round_trip(tree13, recorded):
    from isotope_tpu.metrics.query import MetricStore, parse_exposition

    _, tl = recorded
    text = tm.prometheus_text(tree13, tl)
    samples = parse_exposition(text)
    assert samples
    # every timeline sample carries a timestamp; one per service x
    # window for the per-service families
    svc_samples = [
        s for s in samples if s.name == "timeline_service_requests_total"
    ]
    assert all(s.timestamp_ms is not None for s in svc_samples)
    per_svc: dict = {}
    for s in svc_samples:
        per_svc.setdefault(s.labels["service"], []).append(s)
    for name, rows in per_svc.items():
        assert len(rows) == tl.num_windows, name
        ts = [r.timestamp_ms for r in rows]
        assert ts == sorted(ts) and len(set(ts)) == len(ts)
    # instant queries read the LATEST sample: the cumulative total
    store = MetricStore.from_text(text, float(tl.window_s))
    total = store.query_value("timeline_client_requests_total")
    assert total == float(tl.count)
    one = next(iter(per_svc))
    got = store.query_value(
        f'timeline_service_requests_total{{service="{one}"}}'
    )
    assert got == max(r.value for r in per_svc[one])


def test_label_escaping_round_trips():
    from isotope_tpu.metrics.prometheus import timestamped_series
    from isotope_tpu.metrics.query import parse_exposition

    out: list = []
    nasty = 'svc"with\\quotes\nand-newline'
    timestamped_series(
        out, "timeline_test_total", "h", "counter",
        [({"service": nasty}, 1.0, 1000), ({"service": nasty}, 2.0,
                                           2000)],
    )
    samples = parse_exposition("\n".join(out))
    assert len(samples) == 2
    assert samples[0].labels["service"] == nasty
    assert samples[1].timestamp_ms == 2000


def test_untimestamped_duplicates_still_sum():
    from isotope_tpu.metrics.query import MetricStore, Sample

    store = MetricStore(
        [
            Sample("m", {"a": "x"}, 1.0),
            Sample("m", {"a": "x"}, 2.0),
        ],
        duration_s=1.0,
    )
    assert store.query_value('m{a="x"}') == 3.0


# -- monitor windows ---------------------------------------------------------


def test_monitor_window_rows_and_legacy_rows(tmp_path, tree13,
                                             recorded):
    from isotope_tpu.metrics import monitor
    from isotope_tpu.metrics.alarms import standard_queries

    _, tl = recorded
    queries = standard_queries("t", cpu_lim=1e9, mem_lim=1e9)
    rows = monitor.evaluate_windows(
        queries, tm.window_stores(tree13, tl), run_label="t"
    )
    assert rows
    assert all(r.window_index is not None for r in rows)
    assert all(r.sim_time_s is not None for r in rows)
    assert {r.window_index for r in rows} == set(
        range(tl.num_windows)
    )
    # a breaching limit yields an onset at the first active window
    hot = monitor.evaluate_windows(
        standard_queries("t", cpu_lim=1e-9, mem_lim=1e9),
        tm.window_stores(tree13, tl), run_label="t",
    )
    onset = monitor.first_alarm_onset(hot)
    assert onset is not None and onset.window_index == 0
    # sink round-trip: windowed rows AND legacy (pre-field) rows read
    # back side by side; alarms() keeps working on both shapes
    sink = monitor.MonitorSink(tmp_path / "monitor.jsonl")
    sink.write([onset])
    with open(sink.path, "a") as f:
        f.write(json.dumps({
            "monitor": "legacy", "status": "ALARM", "value": 1.0,
            "detail": "old row", "run_label": "t",
        }) + "\n")
    back = sink.read()
    assert back[0].window_index == 0
    assert back[1].window_index is None  # legacy default
    assert len(sink.alarms()) == 2


# -- vet cost model ----------------------------------------------------------


def test_vet_accounts_timeline_carries(tree13, tl_sim, monkeypatch):
    from isotope_tpu.analysis import costmodel

    plain = Simulator(tree13)
    assert costmodel.timeline_bytes(plain) == 0.0
    tb = costmodel.timeline_bytes(tl_sim)
    assert tb > 0.0
    est_plain = costmodel.estimate_run(plain, 256)
    est_tl = costmodel.estimate_run(tl_sim, 256)
    assert est_tl.timeline_bytes == tb
    assert est_tl.peak_bytes_at_block == pytest.approx(
        est_plain.peak_bytes_at_block + tb
    )
    # VET-M003 info finding when the carries exceed the share of a
    # (tiny, injected) device capacity
    monkeypatch.setenv(costmodel.ENV_DEVICE_BYTES, str(tb * 2))
    est_small = costmodel.estimate_run(tl_sim, 256)
    findings = costmodel.timeline_findings(est_small)
    assert [f.rule for f in findings] == ["VET-M003"]
    assert findings[0].severity == "info"
    # a roomy share threshold silences it
    monkeypatch.setenv(costmodel.ENV_TIMELINE_SHARE, "0.99")
    assert costmodel.timeline_findings(est_small) == []


# -- closed loop -------------------------------------------------------------


def test_closed_loop_timeline(tree13):
    sim = Simulator(
        tree13, SimParams(timeline=True, timeline_window_s=0.5)
    )
    load = LoadModel(kind="closed", qps=500.0, connections=16)
    s, tl = sim.run_timeline(load, 512, KEY, block_size=128)
    assert float(np.asarray(tl.arrivals).sum()) == float(s.count)
    assert tl.num_windows >= 1
