"""Sparse call-slot step encoding (the star-10k wide-level mitigation).

A skewed level — one ~2,000-step hub among thousands of single-step
leaves, the star archetype's shape — used to materialize a dense
(hops x Pmax) step grid per request.  The sparse encoding keeps one
dynamic slot per call-bearing step and folds pure-sleep steps into
static constants (engine._SparseSteps).  These tests force the sparse
path on small graphs (SimParams.sparse_level_elems=1) and pin it
against the dense path on the same RNG draws: both encodings consume
identical (n, H) random tensors, so outcomes must agree to float
tolerance.
"""
import dataclasses

import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator

KEY = jax.random.PRNGKey(7)

# a skewed level: hub has a long mixed script (sleeps between calls),
# its siblings are plain leaves — hub and leaves share depth 1
SKEWED = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: hub}, {call: s0}, {call: s1}, {call: s2}]
- name: hub
  script:
  - sleep: 1ms
  - call: w0
  - sleep: 2ms
  - call: w1
  - call: w2
  - sleep: 3ms
  - call: w3
- name: s0
- name: s1
- name: s2
- name: w0
  script: [{sleep: 5ms}]
- name: w1
- name: w2
  script: [{sleep: 1ms}]
- name: w3
"""

# sparse_tiling=False pins the TRUE sparse call-slot encoding; the
# dense-blocked tiling that normally mitigates skewed levels first has
# its own equivalence pins in tests/test_sparse_tiles.py
SPARSE = SimParams(sparse_level_elems=1, sparse_tiling=False)
LOAD = LoadModel(kind="open", qps=0.4 / SimParams().cpu_time_s)


def both_encodings(yaml_text, load=LOAD, n=20_000, chaos=(), **kw):
    g = ServiceGraph.from_yaml(yaml_text)
    dense = Simulator(compile_graph(g), SimParams(**kw), chaos)
    sparse = Simulator(
        compile_graph(g),
        SimParams(sparse_level_elems=1, sparse_tiling=False, **kw),
        chaos,
    )
    # the threshold actually flipped the encoding somewhere
    assert all(lvl.sparse is None for lvl in dense._levels)
    assert any(lvl.sparse is not None for lvl in sparse._levels)
    rd = dense.run(load, n, KEY)
    rs = sparse.run(load, n, KEY)
    return rd, rs


def assert_same(rd, rs):
    np.testing.assert_allclose(
        np.asarray(rd.client_latency), np.asarray(rs.client_latency),
        rtol=1e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(rd.client_error), np.asarray(rs.client_error)
    )
    np.testing.assert_array_equal(
        np.asarray(rd.hop_sent), np.asarray(rs.hop_sent)
    )
    np.testing.assert_allclose(
        np.asarray(rd.hop_latency), np.asarray(rs.hop_latency),
        rtol=1e-5, atol=1e-9,
    )
    np.testing.assert_allclose(
        np.asarray(rd.hop_start), np.asarray(rs.hop_start),
        rtol=1e-5, atol=1e-9,
    )


@pytest.mark.slow
def test_sparse_matches_dense_skewed_level():
    assert_same(*both_encodings(SKEWED))


@pytest.mark.slow
def test_sparse_matches_dense_with_error_rates():
    yaml_text = SKEWED.replace(
        "- name: hub\n", "- name: hub\n  errorRate: 30%\n"
    ).replace("- name: w1\n", "- name: w1\n  errorRate: 20%\n")
    assert_same(*both_encodings(yaml_text))


def test_sparse_matches_dense_with_send_probability():
    yaml_text = SKEWED.replace(
        "  - call: w1\n",
        "  - call: {service: w1, probability: 60}\n",
    )
    assert_same(*both_encodings(yaml_text))


@pytest.mark.slow
def test_sparse_matches_dense_with_retries():
    # retries without timeouts stay transport-free (500-triggered only),
    # so the sparse encoding remains valid under multi-attempt calls
    yaml_text = SKEWED.replace(
        "  - call: w3\n",
        "  - call: {service: w3, retries: 2}\n",
    ).replace("- name: w3\n", "- name: w3\n  errorRate: 40%\n")
    assert_same(*both_encodings(yaml_text))


def test_sparse_exact_latency_under_det():
    # deterministic quiet-load: the hub's latency is the exact sum of
    # its steps — sleeps (static part) and call round trips (dynamic)
    g = ServiceGraph.from_yaml(SKEWED)
    p = dataclasses.replace(
        SPARSE, service_time="deterministic"
    )
    sim = Simulator(compile_graph(g), p)
    assert any(lvl.sparse is not None for lvl in sim._levels)
    res = sim.run(LoadModel(kind="open", qps=0.001), 8, KEY)
    cpu = p.cpu_time_s
    net = p.network.one_way(0.0)
    # hub: 1ms + (w0: 2net+cpu+5ms) + 2ms + (w1: 2net+cpu) +
    #      (w2: 2net+cpu+1ms) + 3ms + (w3: 2net+cpu)
    hub = (
        0.001 + 0.002 + 0.003
        + (2 * net + cpu + 0.005)
        + (2 * net + cpu)
        + (2 * net + cpu + 0.001)
        + (2 * net + cpu)
        + cpu
    )
    # entry: concurrent max(hub-call, leaf calls) + cpu; client adds
    # the entry wire round trip
    total = 2 * net + cpu + max(2 * net + hub, 2 * net + cpu)
    np.testing.assert_allclose(
        np.asarray(res.client_latency), total, rtol=1e-5
    )


def test_sparse_active_with_timeouts_and_chaos():
    # transport failures no longer force the dense fallback: the
    # per-slot fail scatter-min keeps the encoding valid (BASELINE
    # configs[3] — 10k-service graph WITH retries/timeouts — needs it)
    from isotope_tpu.sim.config import ChaosEvent

    to = SKEWED.replace(
        "  - call: w1\n", "  - call: {service: w1, timeout: 1s}\n"
    )
    sim = Simulator(
        compile_graph(ServiceGraph.from_yaml(to)), SPARSE
    )
    assert any(lvl.sparse is not None for lvl in sim._levels)

    sim2 = Simulator(
        compile_graph(ServiceGraph.from_yaml(SKEWED)), SPARSE,
        (ChaosEvent(service="w0", start_s=1.0, end_s=2.0,
                    replicas_down=None),),
    )
    assert any(lvl.sparse is not None for lvl in sim2._levels)


def test_sparse_matches_dense_with_firing_timeouts():
    # a timeout short enough that w0's 5ms sleep busts it: the hub
    # transport-fails at that step, truncating its script — later
    # steps (w1/w2/w3 calls, the 3ms sleep) must not run
    yaml_text = SKEWED.replace(
        "  - call: w0\n", "  - call: {service: w0, timeout: 3ms}\n"
    )
    rd, rs = both_encodings(yaml_text)
    assert_same(rd, rs)
    # the truncation actually fires: the hub hop 500s (a downstream
    # 500 does NOT fail the entry), and w1/w2/w3 are never sent while
    # w0 (the timed-out attempt) is
    err = np.asarray(rd.hop_error)
    sent = np.asarray(rd.hop_sent)
    assert err[:, 1].all()
    assert sent[:, 5].all() and not sent[:, 6:9].any()


def test_sparse_matches_dense_with_mid_script_timeout():
    # timeout on a MIDDLE call (w1) leaves earlier steps intact and
    # kills only the tail — exercises partial sleep prefixes
    yaml_text = SKEWED.replace(
        "  - call: w1\n",
        "  - call: {service: w1, timeout: 0.1ms}\n",
    )
    assert_same(*both_encodings(yaml_text))


def test_sparse_matches_dense_with_timeout_retries():
    # retries re-attempt timed-out calls; attempt durations stack
    # inside the failing step before truncation
    yaml_text = SKEWED.replace(
        "  - call: w1\n",
        "  - call: {service: w1, timeout: 0.2ms, retries: 2}\n",
    )
    assert_same(*both_encodings(yaml_text))


def test_sparse_matches_dense_concurrent_slot_timeout():
    # two calls SHARING one (hop, step) slot — a concurrent fan-out
    # step inside the hub — where one of them times out: exercises the
    # non-identity call_slot scatter for both the duration max and the
    # slot-failure or-reduction, plus truncation of the steps after it
    yaml_text = SKEWED.replace(
        "  - call: w1\n  - call: w2\n",
        "  - [{call: {service: w1, timeout: 0.1ms}}, {call: w2}]\n",
    )
    g = ServiceGraph.from_yaml(yaml_text)
    sparse_sim = Simulator(compile_graph(g), SPARSE)
    lv = [l for l in sparse_sim._levels if l.sparse is not None]
    assert lv and any(l.sparse.call_slot is not None for l in lv)
    rd, rs = both_encodings(yaml_text)
    assert_same(rd, rs)
    # the timeout fires on w1 while its slot-mate w2 still runs, and
    # the steps after the fan-out (3ms sleep, w3 call) are truncated
    sent = np.asarray(rd.hop_sent)
    hub_err = np.asarray(rd.hop_error)[:, 1]
    assert hub_err.all()
    i_w2 = 5 + 2  # level-2 hops start at 5: w0, w1, w2, w3
    i_w3 = 5 + 3
    assert sent[:, i_w2].all() and not sent[:, i_w3].any()


def test_sparse_matches_dense_with_chaos_total():
    from isotope_tpu.sim.config import ChaosEvent

    # w2 fully down in a window: hub requests arriving inside it
    # transport-fail at the w2 step, others run the full script
    n = 20_000
    dur = n / LOAD.qps
    chaos = (
        ChaosEvent(
            service="w2",
            start_s=0.25 * dur,
            end_s=0.75 * dur,
            replicas_down=None,
        ),
    )
    rd, rs = both_encodings(SKEWED, chaos=chaos)
    assert_same(rd, rs)
    # the window genuinely bit: hub hops transport-failing at the w2
    # step 500 (without failing the entry), only inside the window
    errs = np.asarray(rd.hop_error)[:, 1]
    assert 0 < errs.sum() < n


def test_sparse_matches_dense_with_chaos_and_timeout():
    from isotope_tpu.sim.config import ChaosEvent

    yaml_text = SKEWED.replace(
        "  - call: w3\n",
        "  - call: {service: w3, timeout: 0.2ms}\n",
    )
    n = 20_000
    dur = n / LOAD.qps
    chaos = (
        ChaosEvent(
            service="w0",
            start_s=0.25 * dur,
            end_s=0.5 * dur,
            replicas_down=None,
        ),
    )
    assert_same(*both_encodings(yaml_text, chaos=chaos, n=n))


def test_leaf_levels_use_static_busy():
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(SKEWED)))
    assert sim._levels[-1].leaf_busy is not None
