"""Runner / sweep driver + CLI tests."""
import json
import pathlib

import pytest

from isotope_tpu import cli
from isotope_tpu.runner import load_toml, run_experiment

TOPO = pathlib.Path(__file__).parent.parent / "examples/topologies/canonical.yaml"


def small_toml(tmp_path, **sim_overrides):
    sim = {"num_requests": 2000, "seed": 7}
    sim.update(sim_overrides)
    sim_lines = "\n".join(
        f'{k} = {json.dumps(v)}' for k, v in sim.items()
    )
    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE", "ISTIO"]

[client]
qps = [500]
num_concurrent_connections = [8]
duration = "120s"
load_kind = "open"

[sim]
{sim_lines}
"""
    )
    return cfg


def test_load_toml_schema(tmp_path):
    cfg = load_toml(small_toml(tmp_path))
    assert cfg.topology_paths == (str(TOPO),)
    assert [e.name for e in cfg.environments] == ["NONE", "ISTIO"]
    assert cfg.qps == (500.0,)
    assert cfg.connections == (8,)
    assert cfg.duration_s == 120.0
    assert cfg.num_requests == 2000
    # ISTIO default == "both": two proxy passes of per-edge latency tax
    istio = cfg.environments[1]
    assert istio.client_proxy and istio.server_proxy
    base = cfg.sim_params()
    assert istio.apply(base).network.base_latency_s == pytest.approx(
        base.network.base_latency_s + 500e-6
    )


def test_load_toml_qps_max_and_env_override(tmp_path):
    cfg_path = tmp_path / "exp.toml"
    cfg_path.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["CUSTOM"]

[environment.CUSTOM]
extra_hop_latency = "2ms"

[client]
qps = "max"
"""
    )
    cfg = load_toml(cfg_path)
    assert cfg.qps == (None,)
    assert cfg.environments[0].extra_hop_latency_s == pytest.approx(0.002)


def test_unknown_environment_rejected(tmp_path):
    cfg_path = tmp_path / "exp.toml"
    cfg_path.write_text(
        f'topology_paths = ["{TOPO}"]\nenvironments = ["WAT"]\n'
    )
    with pytest.raises(ValueError, match="WAT"):
        load_toml(cfg_path)


def test_run_experiment_grid_and_artifacts(tmp_path):
    cfg = load_toml(small_toml(tmp_path))
    out = tmp_path / "results"
    results = run_experiment(cfg, out_dir=out)
    # 1 topology x 2 envs x 1 conn x 1 qps
    assert len(results) == 2
    labels = [r.label for r in results]
    assert labels == [
        "canonical_none_500qps_8c",
        "canonical_istio_500qps_8c",
    ]
    # ISTIO pays the sidecar tax on every hop
    assert results[1].flat["p50"] > results[0].flat["p50"]
    # artifacts
    lines = (out / "results.jsonl").read_text().splitlines()
    assert len(lines) == 2 and json.loads(lines[0])["Labels"] == labels[0]
    csv = (out / "benchmark.csv").read_text().splitlines()
    assert csv[0].startswith("Labels,StartTime")
    assert len(csv) == 3
    for r in results:
        assert (out / f"{r.label}.json").exists()
        prom = (out / f"{r.label}.prom").read_text()
        assert "service_request_duration_seconds" in prom


def test_cli_simulate_flat(tmp_path, capsys):
    rc = cli.main(
        [
            "simulate",
            str(TOPO),
            "--qps", "200",
            "--duration", "100s",
            "--load-kind", "open",
            "--max-requests", "2000",
            "--flat",
            "--prometheus", str(tmp_path / "m.prom"),
        ]
    )
    assert rc == 0
    cap = capsys.readouterr()
    flat = json.loads(cap.out)
    assert flat["RequestedQPS"] == 200
    assert flat["p99"] >= flat["p50"] > 0
    # the five service series + the two sim-side resource series
    assert (tmp_path / "m.prom").read_text().count("# TYPE") == 7


def test_cli_sweep(tmp_path, capsys):
    cfg = small_toml(tmp_path)
    out = tmp_path / "res"
    rc = cli.main(["sweep", str(cfg), "-o", str(out)])
    assert rc == 0
    assert (out / "benchmark.csv").exists()


def test_cli_simulate_unknown_environment_errors(capsys):
    rc = cli.main(["simulate", str(TOPO), "--environment", "NOPE"])
    assert rc == 1
    assert "unknown environment" in capsys.readouterr().err


def test_heavy_tail_toml_plumbing(tmp_path):
    cfg = load_toml(
        small_toml(tmp_path, service_time="pareto", service_time_param=1.5)
    )
    params = cfg.sim_params()
    assert params.service_time == "pareto"
    assert params.service_time_param == 1.5
    # and it actually runs
    results = run_experiment(
        load_toml(small_toml(tmp_path, service_time="lognormal",
                             service_time_param=2.0, num_requests=500))
    )
    assert results and results[0].flat["p50"] > 0


@pytest.mark.slow
@pytest.mark.slow
def test_sweep_profile_captures_traces(tmp_path):
    import glob

    from isotope_tpu.runner import load_toml, run_experiment

    cfg = small_toml(tmp_path, num_requests=500)
    prof = tmp_path / "prof"
    run_experiment(load_toml(cfg), profile_dir=str(prof))
    # one trace directory per run, each with an xplane dump
    runs = sorted(p.name for p in prof.iterdir())
    assert runs == [
        "canonical_istio_500qps_8c", "canonical_none_500qps_8c"
    ]
    for r in runs:
        assert glob.glob(str(prof / r / "**" / "*.xplane.pb"),
                         recursive=True)
