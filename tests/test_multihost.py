"""Multi-host scale-out (ISSUE 8): the emulated multi-host twin, the
DCN-aware merge, collective/compute overlap, and the DCN chaos path —
all on the 8-device virtual CPU mesh (conftest)."""
import dataclasses

import jax
import jax.tree_util as jtu
import numpy as np
import pytest

from isotope_tpu import telemetry
from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.parallel import (
    EmulatedMesh,
    MeshSpec,
    ShardedSimulator,
    build_mesh,
    make_mesh,
)
from isotope_tpu.resilience import (
    TRANSIENT,
    InjectedFault,
    ResiliencePolicy,
    classify,
    execution_rungs,
    faults,
    run_ladder,
)
from isotope_tpu.sim import LoadModel, SimParams

YAML = """
defaults:
  responseSize: 1 KiB
services:
- name: entry
  isEntrypoint: true
  script:
  - - call: x
    - call: y
  - call: z
- name: x
  numReplicas: 2
- name: y
  script:
  - call: z
- name: z
"""
OPEN = LoadModel(kind="open", qps=2000.0)
CLOSED = LoadModel(kind="closed", qps=None, connections=16)
KEY = jax.random.PRNGKey(23)


@pytest.fixture(scope="module")
def compiled():
    return compile_graph(ServiceGraph.from_yaml(YAML))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    telemetry.reset()
    yield
    faults.clear()


def _ulp_diff(a, b) -> float:
    a, b = np.asarray(a), np.asarray(b)
    if a.dtype == bool:
        return 0.0 if (a == b).all() else np.inf
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    same = (a64 == b64) | (np.isinf(a64) & np.isinf(b64)
                           & (np.sign(a64) == np.sign(b64)))
    sp = np.spacing(
        np.maximum(np.abs(a), np.abs(b)).astype(np.float32)
    ).astype(np.float64)
    with np.errstate(invalid="ignore"):
        diff = np.abs(a64 - b64) / np.where(sp > 0, sp, 1.0)
    return float(np.max(np.where(same, 0.0, diff)))


def _assert_close(a, b, max_ulp):
    for (path, want), (_, got) in zip(
        jtu.tree_flatten_with_path(a)[0],
        jtu.tree_flatten_with_path(b)[0],
    ):
        assert _ulp_diff(want, got) <= max_ulp, jtu.keystr(path)


# -- emulated multi-host twin ----------------------------------------------


def test_emulated_two_hosts_by_eight_devices(compiled):
    """2 x 8 emulated hosts — 16 shards replayed on one device."""
    twin = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=4, svc=2, slices=2))
    )
    assert twin.n_shards == 16
    assert twin.dcn_axes == ("slice",)
    s = twin.run_emulated(OPEN, 16384, KEY, block_size=1024)
    assert int(s.count) == 16384
    assert int(s.hop_events) == 16384 * compiled.num_hops
    assert 0.0 < s.mean_latency_s < 10.0
    dur = np.asarray(s.metrics.duration_hist)
    inc = np.asarray(s.metrics.incoming_total)
    for svc in range(compiled.num_services):
        assert dur[svc].sum() == pytest.approx(inc[svc])


def test_emulated_twin_deterministic(compiled):
    twin = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=8, svc=2, slices=4))
    )
    a = twin.run_emulated(OPEN, 4096, KEY, block_size=512)
    b = twin.run_emulated(OPEN, 4096, KEY, block_size=512)
    _assert_close(a, b, max_ulp=0.0)


def test_emulated_mesh_rejects_shard_map_entry_points(compiled):
    twin = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=4, svc=2, slices=2))
    )
    with pytest.raises(ValueError, match="_emulated twin"):
        twin.run(OPEN, 1024, KEY)
    with pytest.raises(ValueError, match="needs a device mesh"):
        ShardedSimulator(
            compiled,
            EmulatedMesh(MeshSpec(data=4, svc=2, slices=2)),
            params=SimParams(timeline=True),
        ).run_timeline(OPEN, 1024, KEY)


def test_multislice_twin_bit_equal_to_shard_map(compiled):
    """ISSUE acceptance: the emulated multi-host twin (>= 2 emulated
    hosts) merges bit-equal to the shard_map path on CPU."""
    sharded = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2, slices=2))
    )
    dev = sharded.run(OPEN, 8192, KEY, block_size=1024, trim=True)
    jax.block_until_ready(dev.count)
    twin = sharded.run_emulated(OPEN, 8192, KEY, block_size=1024,
                                trim=True)
    _assert_close(dev, twin, max_ulp=0.0)


# -- DCN-aware merge -------------------------------------------------------


def test_dcn_axes_resolved(compiled):
    flat = ShardedSimulator(compiled, make_mesh(4, 2))
    assert flat.dcn_axes == ()
    assert flat.ici_axes == ("data", "svc")
    ms = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2, slices=2))
    )
    assert ms.dcn_axes == ("slice",)
    assert ms.ici_axes == ("data", "svc")
    assert ms.ici_request_axes == ("data",)


def test_hierarchical_merge_matches_flat_statistics(compiled):
    """The ICI-first/DCN-last merge is a pure reassociation: the
    multislice mesh must agree with the flat mesh of the same shard
    count on every integer field and within f32 noise on sums (same
    shard count => identical per-shard RNG streams)."""
    n = 8192
    flat = ShardedSimulator(compiled, make_mesh(4, 2)).run(
        OPEN, n, KEY, block_size=1024
    )
    ms = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2, slices=2))
    ).run(OPEN, n, KEY, block_size=1024)
    # shard index ordering differs ((slice, data, svc) vs (data, svc))
    # but the shard SET is the same 0..7, so totals agree exactly on
    # integer-valued fields
    assert float(ms.count) == float(flat.count)
    assert float(ms.hop_events) == float(flat.hop_events)
    np.testing.assert_array_equal(
        np.asarray(ms.latency_hist), np.asarray(flat.latency_hist)
    )
    np.testing.assert_allclose(
        float(ms.latency_sum), float(flat.latency_sum), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(ms.metrics.duration_hist),
        np.asarray(flat.metrics.duration_hist), rtol=1e-6,
    )


# -- collective/compute overlap --------------------------------------------


@pytest.mark.parametrize("spec", [
    MeshSpec(data=4, svc=2),
    MeshSpec(data=2, svc=2, slices=2),
])
@pytest.mark.parametrize("load,trim", [(OPEN, False), (OPEN, True),
                                       (CLOSED, False)])
@pytest.mark.slow
def test_overlap_equivalence(compiled, spec, load, trim):
    """ISSUE satellite: overlap on == off — exact on integer-valued
    fields, f32 reduction-order noise on float sums (the pipelined
    merge reduces shards-within-block before blocks; off reduces
    blocks-within-shard first)."""
    n = 8192
    off = ShardedSimulator(compiled, build_mesh(spec)).run(
        load, n, KEY, block_size=1024, trim=trim
    )
    on = ShardedSimulator(
        compiled, build_mesh(spec), params=SimParams(overlap=True)
    ).run(load, n, KEY, block_size=1024, trim=trim)
    for f in ("count", "error_count", "hop_events", "win_count",
              "win_error_count", "win_lo", "win_hi"):
        assert float(getattr(on, f)) == float(getattr(off, f)), f
    for f in ("latency_hist", "win_latency_hist"):
        np.testing.assert_array_equal(
            np.asarray(getattr(on, f)), np.asarray(getattr(off, f)), f
        )
    # order-sensitive float reductions: reassociation only
    for f in ("latency_sum", "latency_m2"):
        np.testing.assert_allclose(
            float(getattr(on, f)), float(getattr(off, f)),
            rtol=1e-5, err_msg=f,
        )
    for f in ("latency_min", "latency_max", "end_max"):
        assert float(getattr(on, f)) == float(getattr(off, f)), f
    _assert_close(on.metrics, off.metrics, max_ulp=4.0)
    np.testing.assert_array_equal(
        np.asarray(on.utilization), np.asarray(off.utilization)
    )


@pytest.mark.slow
@pytest.mark.slow
def test_overlap_equivalence_eager(compiled):
    """The satellite's eager pin: under jax.disable_jit the overlap
    body executes its collectives op-by-op and must still reproduce
    the off path's integer fields exactly."""
    n = 2048
    spec = MeshSpec(data=2, svc=2, slices=2)
    with jax.disable_jit():
        off = ShardedSimulator(compiled, build_mesh(spec)).run(
            OPEN, n, KEY, block_size=512
        )
        on = ShardedSimulator(
            compiled, build_mesh(spec), params=SimParams(overlap=True)
        ).run(OPEN, n, KEY, block_size=512)
    assert float(on.count) == float(off.count)
    assert float(on.hop_events) == float(off.hop_events)
    np.testing.assert_array_equal(
        np.asarray(on.latency_hist), np.asarray(off.latency_hist)
    )
    np.testing.assert_allclose(
        float(on.latency_sum), float(off.latency_sum), rtol=1e-6
    )


def test_overlap_off_default_unchanged(compiled):
    """overlap=False (the default) must stay byte-identical to an
    explicitly-off run — the pre-PR single-merge path."""
    a = ShardedSimulator(compiled, make_mesh(4, 2)).run(
        OPEN, 4096, KEY, block_size=1024
    )
    b = ShardedSimulator(
        compiled, make_mesh(4, 2), params=SimParams(overlap=False)
    ).run(OPEN, 4096, KEY, block_size=1024)
    _assert_close(a, b, max_ulp=0.0)


def test_overlap_twin_matches_device_within_reduction_noise(compiled):
    """The emulated twin replays the off-order host merge; with
    overlap on, the device path differs by reduction order only."""
    spec = MeshSpec(data=2, svc=2, slices=2)
    sharded = ShardedSimulator(
        compiled, build_mesh(spec), params=SimParams(overlap=True)
    )
    dev = sharded.run(OPEN, 8192, KEY, block_size=1024)
    jax.block_until_ready(dev.count)
    twin = sharded.run_emulated(OPEN, 8192, KEY, block_size=1024)
    assert float(dev.count) == float(twin.count)
    np.testing.assert_array_equal(
        np.asarray(dev.latency_hist), np.asarray(twin.latency_hist)
    )
    np.testing.assert_allclose(
        float(dev.latency_sum), float(twin.latency_sum), rtol=1e-5
    )


# -- DCN chaos + taxonomy --------------------------------------------------


def test_dcn_error_signatures_classify_transient():
    for msg in (
        "UNAVAILABLE: MegaScale transfer timed out",
        "XlaRuntimeError: DCN transfer server connection dropped",
        "collective operation timed out waiting for remote slice",
        "barrier timed out after 600s",
        "coordination service agent heartbeat timeout",
        "failed to connect to all addresses; last error: ...",
        "peer task jax_worker/1 failed mid all-reduce",
    ):
        assert classify(RuntimeError(msg)) == TRANSIENT, msg


def test_dcn_collective_site_parses():
    plan = faults.FaultPlan.parse("transient:sharded.dcn_collective:1")
    assert plan.entries[0].site == "sharded.dcn_collective"


def test_dcn_site_fires_only_on_dcn_meshes(compiled):
    faults.install("transient:sharded.dcn_collective:1")
    flat = ShardedSimulator(compiled, make_mesh(4, 2))
    # no slice axis -> the site never runs -> no fault consumed
    flat.run(OPEN, 1024, KEY, block_size=512)
    ms = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2, slices=2))
    )
    with pytest.raises(InjectedFault) as ei:
        ms.run(OPEN, 1024, KEY, block_size=512)
    assert classify(ei.value) == TRANSIENT


def test_dcn_transient_retries_to_identical_results(compiled):
    """ISSUE satellite: a dropped DCN collective is retried by the
    supervisor (no degradation) and the retried run is bit-identical."""
    sharded = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2, slices=2))
    )
    clean = sharded.run(OPEN, 4096, KEY, block_size=1024)
    jax.block_until_ready(clean.count)
    telemetry.reset()
    faults.install("transient:sharded.dcn_collective:1")
    rungs = execution_rungs(
        sharded.sim, sharded, True, OPEN, 4096, KEY, 1024, trim=False
    )
    summary, degraded = run_ladder(
        rungs, ResiliencePolicy(sleep=lambda s: None)
    )
    assert degraded is None
    assert telemetry.counter_get("retries_total") == 1.0
    _assert_close(clean, summary, max_ulp=0.0)


# -- runner integration ----------------------------------------------------


def _config(topo, tmp_path, **kw):
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )

    p = tmp_path / "t.yaml"
    p.write_text(YAML)
    return ExperimentConfig(
        topology_paths=(str(p),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(500.0,),
        connections=(8,),
        duration_s=2.0,
        load_kind="open",
        num_requests=2048,
        **kw,
    )


def test_runner_explicit_mesh_spec_and_record(tmp_path):
    from isotope_tpu.runner.run import run_experiment

    (res,) = run_experiment(_config(YAML, tmp_path, mesh_spec="2x2x2"))
    assert not res.failed
    assert res.flat["_mesh_layout"] == "data=2,svc=2,slice=2"


def test_runner_auto_mesh(tmp_path):
    from isotope_tpu.runner.run import run_experiment

    (res,) = run_experiment(_config(YAML, tmp_path, mesh_spec="auto"))
    assert not res.failed
    assert "_mesh_layout" in res.flat
    assert res.flat["_mesh_layout"].startswith("data=")


def test_runner_env_mesh(tmp_path, monkeypatch):
    from isotope_tpu.parallel.mesh import ENV_MESH
    from isotope_tpu.runner.run import run_experiment

    monkeypatch.setenv(ENV_MESH, "4x2")
    (res,) = run_experiment(_config(YAML, tmp_path))
    assert res.flat["_mesh_layout"] == "data=4,svc=2"


def test_runner_bad_mesh_spec_fails_before_simulating(tmp_path):
    from isotope_tpu.runner.run import run_experiment

    with pytest.raises(ValueError, match=r"mesh"):
        run_experiment(_config(YAML, tmp_path, mesh_spec="nope=1"))


def test_runner_overlap_config_round_trip(tmp_path):
    from isotope_tpu.runner.run import run_experiment

    cfg = _config(YAML, tmp_path, mesh_spec="2x2", overlap=True)
    assert cfg.sim_params().overlap
    (res,) = run_experiment(cfg)
    assert not res.failed
    off = run_experiment(
        dataclasses.replace(cfg, overlap=False)
    )[0]
    assert res.fortio_json["DurationHistogram"]["Count"] == (
        off.fortio_json["DurationHistogram"]["Count"]
    )
