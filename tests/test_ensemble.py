"""Scenario ensembles (ISSUE 14): vmapped/mapped Monte Carlo fleets.

The pins the feature's contract rests on:

- member k of a seeds-only fleet is BIT-IDENTICAL to the solo
  ``run_summary`` with ``fold_in(key, seeds[k])`` (both batching
  modes, open and closed loop);
- ``ensemble`` off (the default SimParams) leaves the solo paths
  byte-identical;
- the sharded fleet == its emulated host-loop twin, bit-for-bit (no
  cross-member collectives exist to reorder float sums);
- member-chunked dispatches == the unchunked fleet;
- the Wilson CI math against the closed form;
- the runner's isotope-ensemble/v1 artifact round-trips, and the
  same-shape case collapse dispatches one fleet for a whole qps
  group.

Shape discipline: the open-loop fleets share ONE (512-request,
256-block) program shape per (width, mode, jitter) so the module pays
a handful of compiles, not one per test.
"""
import dataclasses
import json

import jax
import jax.tree_util as jtu
import numpy as np
import pytest

from isotope_tpu.compiler import compile_ensemble, compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.ensemble import (
    EnsembleSpec,
    doc_member_quantiles,
    norm_ppf,
    parse_jitter_spec,
    wilson_interval,
)

YAML = """
defaults:
  responseSize: 1 KiB
services:
- name: entry
  isEntrypoint: true
  errorRate: 1%
  script:
  - - call: x
    - call: y
  - call: z
- name: x
  numReplicas: 2
- name: y
  script:
  - call: z
- name: z
"""

OPEN = LoadModel(kind="open", qps=2000.0)
KEY = jax.random.PRNGKey(7)
N, BLOCK = 512, 256  # two blocks: the scan carry is exercised


@pytest.fixture(scope="module")
def compiled():
    return compile_graph(ServiceGraph.from_yaml(YAML))


@pytest.fixture(scope="module")
def sim(compiled):
    return Simulator(compiled)


@pytest.fixture(scope="module")
def ens3(sim):
    """The module's canonical 3-member seeds-only fleet (map mode)."""
    return sim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(3, mode="map"), block_size=BLOCK
    )


@pytest.fixture(scope="module")
def solos3(sim):
    """The three solo twins of ``ens3``'s members."""
    return [
        sim.run_summary(
            OPEN, N, jax.random.fold_in(KEY, k), block_size=BLOCK
        )
        for k in range(3)
    ]


def _leaves_equal(a, b):
    la, lb = jtu.tree_leaves(a), jtu.tree_leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
        for x, y in zip(la, lb)
    )


# -- member == solo bit-equality ---------------------------------------


def test_member_bit_equals_solo_map(ens3, solos3):
    for k in range(3):
        assert _leaves_equal(solos3[k], ens3.member(k)), k


def test_member_bit_equals_solo_vmap(sim, solos3):
    ens = sim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(3, mode="vmap"),
        block_size=BLOCK,
    )
    for k in range(3):
        assert _leaves_equal(solos3[k], ens.member(k)), k


def test_member_bit_equals_solo_closed(sim):
    load = LoadModel(kind="closed", qps=1500.0, connections=8)
    ens = sim.run_ensemble(
        load, 256, KEY, EnsembleSpec.of(2), block_size=128
    )
    solo = sim.run_summary(
        load, 256, jax.random.fold_in(KEY, 1), block_size=128
    )
    assert _leaves_equal(solo, ens.member(1))


def test_member_seeds_are_fold_indices(sim, solos3):
    # explicit non-contiguous seeds: member order follows the spec
    # (same width/shape/mode as ens3 — the compiled fleet is reused)
    spec = EnsembleSpec(seeds=(5, 1, 2), mode="map")
    ens = sim.run_ensemble(OPEN, N, KEY, spec, block_size=BLOCK)
    assert _leaves_equal(solos3[1], ens.member(1))
    assert not _leaves_equal(solos3[0], ens.member(0))  # seed 5 != 0


# -- ensemble off == byte-identical ------------------------------------


def test_ensemble_off_solo_paths_byte_identical(sim, compiled,
                                                solos3):
    armed = Simulator(compiled, SimParams(ensemble=4))
    # the ensemble knob is not a traced constant: the armed engine
    # must share the solo signature (and so the compiled executable)
    assert armed.signature == sim.signature
    b = armed.run_summary(
        OPEN, N, jax.random.fold_in(KEY, 0), block_size=BLOCK
    )
    assert _leaves_equal(solos3[0], b)


def test_default_params_ensemble_off():
    assert SimParams().ensemble == 0
    with pytest.raises(ValueError, match="ensemble"):
        SimParams(ensemble=-1)


# -- chunking -----------------------------------------------------------


def test_chunked_equals_unchunked(sim, ens3):
    chunked = sim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(3, mode="map"),
        block_size=BLOCK, chunk=2,
    )
    assert chunked.chunk == 2
    assert _leaves_equal(ens3.summaries, chunked.summaries)


def test_ensemble_chunk_balanced():
    from isotope_tpu.analysis import costmodel

    # 33 members over a 17-member budget (capacity 20 at the 0.85
    # fill): two chunks of 17 + 16, not 17 + 16 + a padded third
    assert costmodel.ensemble_chunk(33, 1.0, 20.0) == 17
    # 33 over a 15-member budget: 3 balanced chunks of 11
    assert costmodel.ensemble_chunk(33, 1.0, 15.0 / 0.85 + 1e-9) == 11
    # fits -> whole fleet; unknown capacity -> whole fleet
    assert costmodel.ensemble_chunk(8, 1.0, 1e9) == 8
    assert costmodel.ensemble_chunk(8, 1.0, None) == 8


def test_vet_m004_reports_auto_chunk():
    from isotope_tpu.analysis import costmodel

    est = costmodel.CostEstimate(
        block_requests=256, trace_requests=8, jaxpr=None,
        peak_bytes_at_block=1e6, flops_at_block=1.0, critical_path=1,
        segments=[], capacity_bytes=4e6,
    )
    findings = costmodel.ensemble_findings(est, members=16)
    assert [f.rule for f in findings] == ["VET-M004"]
    assert "chunks of" in findings[0].message
    # fits: silent
    assert costmodel.ensemble_findings(est, members=2) == []


# -- sharded == emulated twin ------------------------------------------


@pytest.mark.slow
@pytest.mark.slow
def test_sharded_fleet_bit_equals_emulated_twin(compiled):
    from isotope_tpu.parallel import (
        EmulatedMesh,
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    sh = ShardedSimulator(compiled, build_mesh(MeshSpec(data=4, svc=2)))
    spec = EnsembleSpec.of(9)  # 9 over 8 shards: padding exercised
    dev = sh.run_ensemble(OPEN, 256, KEY, spec, block_size=128)
    emu = sh.run_ensemble_emulated(OPEN, 256, KEY, spec,
                                   block_size=128)
    assert _leaves_equal(dev.summaries, emu.summaries)
    # the EmulatedMesh twin (same shard count, no devices) replays
    # the same member partition bit-for-bit; its shard_map entry
    # points reject loudly
    esh = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=4, svc=2))
    )
    twin = esh.run_ensemble_emulated(OPEN, 256, KEY, spec,
                                     block_size=128)
    assert _leaves_equal(dev.summaries, twin.summaries)
    with pytest.raises(ValueError, match="emulated"):
        esh.run_ensemble(OPEN, 256, KEY, spec, block_size=128)
    # over-wide fleets split into sequential per-shard ROUNDS (the
    # mesh edition of member chunking): chunk=1 forces 2 rounds of
    # width-1 dispatches, bit-equal to the one-round fleet — on the
    # device path AND its emulated twin
    narrow_spec = EnsembleSpec.of(9, chunk=1)
    narrow = sh.run_ensemble(OPEN, 256, KEY, narrow_spec,
                             block_size=128)
    assert narrow.chunk == 1
    assert _leaves_equal(dev.summaries, narrow.summaries)
    narrow_twin = esh.run_ensemble_emulated(
        OPEN, 256, KEY, narrow_spec, block_size=128
    )
    assert _leaves_equal(dev.summaries, narrow_twin.summaries)


# -- per-member physics perturbations ----------------------------------


def test_cpu_and_error_scales_move_member_physics(sim):
    spec = EnsembleSpec(
        seeds=(0, 1),
        cpu_scale=np.array([0.25, 4.0]),
        error_scale=np.array([1e-6, 50.0]),
        mode="map",
    )
    ens = sim.run_ensemble(OPEN, N, KEY, spec, block_size=BLOCK)
    lat = np.asarray(ens.summaries.latency_sum)
    errs = np.asarray(ens.summaries.error_count)
    assert lat[1] > lat[0]
    assert errs[1] > errs[0]


def test_qps_scale_moves_member_offered(sim, ens3):
    # qps jitter reshapes the traced ARGS only (jittered stays False:
    # the plain width-3 fleet program serves it)
    spec = EnsembleSpec(
        seeds=(0, 1, 2), qps_scale=np.array([0.5, 2.0, 1.0]),
        mode="map",
    )
    ens = sim.run_ensemble(OPEN, N, KEY, spec, block_size=BLOCK)
    assert not spec.jittered
    assert ens.offered_qps[0] == pytest.approx(1000.0)
    assert ens.offered_qps[1] == pytest.approx(4000.0)
    # member 2 runs at the base rate with seed 2: bit-equal to ens3's
    assert _leaves_equal(ens3.member(2), ens.member(2))


def test_jitter_spec_deterministic():
    a = EnsembleSpec.from_jitter(4, qps_jitter=0.1, cpu_jitter=0.2,
                                 jitter_seed=3)
    b = EnsembleSpec.from_jitter(4, qps_jitter=0.1, cpu_jitter=0.2,
                                 jitter_seed=3)
    assert np.array_equal(a.qps_scale, b.qps_scale)
    assert np.array_equal(a.cpu_scale, b.cpu_scale)
    assert a.error_scale is None


# -- spec validation + vet rules ---------------------------------------


def test_spec_validation():
    with pytest.raises(ValueError, match="duplicate"):
        EnsembleSpec(seeds=(1, 1, 2)).check()
    with pytest.raises(ValueError, match="zero members"):
        EnsembleSpec(seeds=()).check()
    EnsembleSpec(seeds=(1, 1)).check(allow_duplicate_seeds=True)
    with pytest.raises(ValueError, match="shape"):
        EnsembleSpec(seeds=(0, 1), cpu_scale=np.ones(3))
    with pytest.raises(ValueError, match="positive"):
        EnsembleSpec(seeds=(0,), qps_scale=np.array([-1.0]))
    with pytest.raises(ValueError, match="mode"):
        EnsembleSpec(seeds=(0,), mode="tensor")
    with pytest.raises(ValueError, match="chunk"):
        EnsembleSpec(seeds=(0,), chunk=0)


def test_parse_jitter_spec():
    j = parse_jitter_spec("qps=0.1, cpu=0.05,error=0.2,seed=9")
    assert j == {"qps_jitter": 0.1, "cpu_jitter": 0.05,
                 "error_jitter": 0.2, "jitter_seed": 9}
    assert parse_jitter_spec(None)["qps_jitter"] == 0.0
    with pytest.raises(ValueError, match="axis"):
        parse_jitter_spec("latency=3")
    with pytest.raises(ValueError, match="axis=value"):
        parse_jitter_spec("qps")


def test_lint_ensemble_vet_t023():
    from isotope_tpu.analysis import topo_lint

    dup = topo_lint.lint_ensemble(EnsembleSpec(seeds=(3, 3, 4)))
    assert [f.rule for f in dup] == ["VET-T023"]
    assert "duplicate" in dup[0].message
    zero = topo_lint.lint_ensemble(EnsembleSpec(seeds=()))
    assert [f.rule for f in zero] == ["VET-T023"]
    assert topo_lint.lint_ensemble(EnsembleSpec.of(4)) == []
    assert topo_lint.lint_ensemble(None) == []


def test_vet_simulator_ensemble_verdicts(sim, monkeypatch):
    from isotope_tpu.analysis import costmodel, vet_simulator

    monkeypatch.setenv(costmodel.ENV_DEVICE_BYTES, "1000000")
    report = vet_simulator(
        sim, OPEN, block_requests=256, trace=False,
        ensemble=EnsembleSpec.of(64),
    )
    rules = {f.rule for f in report.findings}
    assert "VET-M004" in rules
    assert report.meta["ensemble"]["members"] == 64
    assert 1 <= report.meta["ensemble"]["chunk"] < 64
    bad = vet_simulator(
        sim, OPEN, block_requests=256, trace=False,
        ensemble=EnsembleSpec(seeds=(1, 1)),
    )
    assert "VET-T023" in {f.rule for f in bad.findings}


def test_run_rejects_bad_specs(sim):
    with pytest.raises(ValueError, match="duplicate"):
        sim.run_ensemble(
            OPEN, 64, KEY, EnsembleSpec(seeds=(1, 1)), block_size=64
        )
    with pytest.raises(ValueError, match="EnsembleSpec"):
        sim.run_ensemble(OPEN, 64, KEY, None, block_size=64)
    sat = LoadModel(kind="closed", qps=None, connections=8)
    with pytest.raises(ValueError, match="saturated"):
        sim.run_ensemble(
            sat, 64, KEY,
            EnsembleSpec(seeds=(0, 1),
                         cpu_scale=np.array([1.0, 2.0])),
            block_size=64,
        )


# -- CI math ------------------------------------------------------------


def test_wilson_interval_closed_form():
    # closed form at k=3, n=10, z=1.959964:
    #   center = (p + z^2/2n) / (1 + z^2/n), half = z/(1+z^2/n) *
    #   sqrt(p(1-p)/n + z^2/4n^2)
    z = 1.959963984540054
    p, n = 0.3, 10.0
    denom = 1.0 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = z / denom * np.sqrt(p * 0.7 / n + z * z / (4 * n * n))
    lo, hi = wilson_interval(3, 10)
    assert lo == pytest.approx(center - half, abs=1e-9)
    assert hi == pytest.approx(center + half, abs=1e-9)
    # never degenerate at the extremes, never outside [0, 1]
    lo0, hi0 = wilson_interval(0, 20)
    assert lo0 == 0.0 and 0.0 < hi0 < 0.3
    lo1, hi1 = wilson_interval(20, 20)
    assert 0.7 < lo1 < 1.0 and hi1 == 1.0
    assert wilson_interval(0, 0) == (0.0, 1.0)


def test_norm_ppf_reference_values():
    # scipy.stats.norm.ppf reference constants (|rel err| < 1.2e-9)
    assert norm_ppf(0.975) == pytest.approx(1.959963984540054,
                                            abs=1e-7)
    assert norm_ppf(0.5) == pytest.approx(0.0, abs=1e-12)
    assert norm_ppf(0.995) == pytest.approx(2.5758293035489004,
                                            abs=1e-7)
    assert norm_ppf(0.001) == pytest.approx(-3.090232306167813,
                                            abs=1e-6)
    try:  # cross-check against scipy when the env has it
        from scipy.stats import norm

        for q in (0.025, 0.2, 0.7, 0.9999):
            assert norm_ppf(q) == pytest.approx(norm.ppf(q),
                                                abs=1e-7)
    except ImportError:
        pass


def test_slo_violation_counts(ens3):
    p99 = ens3.member_quantiles((0.99,))[:, 0]
    cut = float(np.median(p99))
    est = ens3.slo_violation(cut, quantile=0.99)
    assert est["violations"] == int((p99 > cut).sum())
    assert est["ci_lo"] <= est["p_violation"] <= est["ci_hi"]
    band = ens3.quantile_band(0.99)
    assert band["min_s"] <= band["mid_s"] <= band["max_s"]


# -- artifacts ----------------------------------------------------------


def test_doc_round_trip(ens3):
    doc = json.loads(json.dumps(
        ens3.to_doc(label="t", slo_s=0.01)
    ))
    # v2 since PR 15 (the schema-versioned splitting block); v1
    # documents stay readable
    assert doc["schema"] == "isotope-ensemble/v2"
    assert doc["members"] == 3
    v1 = dict(doc, schema="isotope-ensemble/v1")
    assert np.allclose(doc_member_quantiles(v1),
                       ens3.member_quantiles())
    mq = doc_member_quantiles(doc)
    assert np.allclose(mq, ens3.member_quantiles())
    spec2 = EnsembleSpec.from_dict(doc["spec"])
    assert spec2.seeds == ens3.spec.seeds
    with pytest.raises(ValueError, match="isotope-ensemble"):
        doc_member_quantiles({"schema": "nope"})


def test_compile_ensemble_tables():
    t = compile_ensemble(
        EnsembleSpec.from_jitter(4, cpu_jitter=0.1, mode="map")
    )
    assert t.members == 4 and t.jittered and t.mode == "map"
    plain = compile_ensemble(EnsembleSpec.of(4, mode="map"))
    assert not plain.jittered
    assert np.allclose(np.asarray(plain.cpu_scale), 1.0)


# -- runner integration -------------------------------------------------


def _config(tmp_path, **kw):
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )

    p = tmp_path / "t.yaml"
    p.write_text(YAML)
    return ExperimentConfig(
        topology_paths=(str(p),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(500.0,),
        connections=(8,),
        duration_s=2.0,
        load_kind="open",
        num_requests=256,
        **kw,
    )


def test_runner_ensemble_artifact_and_resume(tmp_path):
    from isotope_tpu.runner.run import run_experiment

    cfg = _config(tmp_path, ensemble=3, ensemble_slo_s=0.25)
    out = str(tmp_path / "out")
    (res,) = run_experiment(cfg, out_dir=out)
    assert not res.failed
    assert res.flat["_ensemble"] == 3
    assert res.ensemble is not None
    path = tmp_path / "out" / f"{res.label}.ensemble.json"
    doc = json.loads(path.read_text())
    assert doc == json.loads(json.dumps(res.ensemble))
    assert doc["slo"]["slo_s"] == pytest.approx(0.25)
    assert len(doc["member_counts"]) == 3
    # the pooled row aggregates every member's requests ...
    assert float(res.fortio_json["DurationHistogram"]["Count"]) == \
        sum(doc["member_counts"])
    # ... but the RATE is per-member: N member worlds of one
    # wall-clock each must not read as N-fold throughput (qps 500
    # open loop -> ActualQPS ~500, not ~1500)
    assert 250.0 < float(res.flat["ActualQPS"]) < 1000.0
    # resume restores from the checkpoint without re-dispatching
    (again,) = run_experiment(cfg, out_dir=out)
    assert again.flat == res.flat


def test_runner_same_shape_collapse_bit_equal(tmp_path):
    """Two qps cells capped to one shape collapse into ONE fleet
    dispatch whose per-cell members bit-equal the uncollapsed
    dispatches."""
    from isotope_tpu import telemetry
    from isotope_tpu.runner.run import run_experiment

    telemetry.reset()
    # num_requests caps both cells at 256 requests -> same shape
    cfg = _config(tmp_path, ensemble=2)
    cfg = dataclasses.replace(cfg, qps=(500.0, 700.0))
    before = telemetry.counter_get("ensemble_group_dispatches")
    results = run_experiment(cfg, out_dir=str(tmp_path / "out"))
    assert len(results) == 2 and not any(r.failed for r in results)
    assert telemetry.counter_get("ensemble_group_dispatches") \
        == before + 1
    # uncollapsed twin of cell 1 (run_index 1, qps 700): member keys
    # fold the checkpoint law fold_in(fold_in(seed_key, idx), seed)
    compiled = compile_graph(ServiceGraph.from_yaml(YAML))
    sim = Simulator(compiled)
    seed_key = jax.random.PRNGKey(cfg.seed)
    cell_key = jax.random.fold_in(seed_key, 1)
    load = LoadModel(kind="open", qps=700.0, connections=8,
                     duration_s=2.0)
    solo = sim.run_ensemble(
        load, 256, cell_key, EnsembleSpec.of(2),
        block_size=sim.default_block_size(), trim=True,
    )
    got = results[1].ensemble_summary
    assert _leaves_equal(solo.summaries, got.summaries)


def test_toml_ensemble_keys(tmp_path):
    from isotope_tpu.runner.config import load_toml

    topo = tmp_path / "t.yaml"
    topo.write_text(YAML)
    cfg_path = tmp_path / "sweep.toml"
    cfg_path.write_text(
        'topology_paths = ["t.yaml"]\n'
        "[client]\n"
        'qps = [500]\n'
        "[sim]\n"
        "ensemble = 8\n"
        'ensemble_jitter = "qps=0.1,cpu=0.05,error=0.2,seed=3"\n'
        'ensemble_slo = "250ms"\n'
    )
    cfg = load_toml(cfg_path)
    assert cfg.ensemble == 8
    assert cfg.ensemble_qps_jitter == 0.1
    assert cfg.ensemble_cpu_jitter == 0.05
    assert cfg.ensemble_error_jitter == 0.2
    assert cfg.ensemble_jitter_seed == 3
    assert cfg.ensemble_slo_s == pytest.approx(0.25)
    spec = cfg.ensemble_spec()
    assert spec.members == 8 and spec.jittered
    assert cfg.sim_params().ensemble == 8
