"""Pallas census kernel (native/census_pallas.py) vs the XLA reference.

Everything runs in INTERPRETER mode on CPU: the kernel body is
evaluated op-by-op with the same jnp semantics the compiled Mosaic
kernel lowers, so the equivalence these tests pin carries to the TPU
path up to hardware rounding (identical op order — the interpreter IS
the reference the kernel must honor).  The ``pallas_census`` flag's
off/auto behavior is pinned too: off-CPU auto resolves to OFF and the
engine never imports the kernel module.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.native import census_pallas
from isotope_tpu.sim import LoadModel, SimParams, Simulator

KEY = jax.random.PRNGKey(11)
OPEN = LoadModel(kind="open", qps=500.0)

YAML = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 2%
  script:
  - call: {service: mid, timeout: 30ms, retries: 2}
  - sleep: 1ms
- name: mid
  errorRate: 5%
  script:
  - - call: {service: leaf, timeout: 10ms, retries: 1}
    - call: {service: leaf2, probability: 60}
- name: leaf
  errorRate: 3%
- name: leaf2
  script:
  - call: deep
- name: deep
"""


def _reference(base, mask, agg, fail=None, err=None):
    p = agg.shape[-1]
    dur = jnp.maximum(base[None], agg) * mask.astype(jnp.float32)[None]
    if fail is not None:
        dur = dur * (
            jnp.arange(p, dtype=jnp.int32) <= fail[:, :, None]
        )
    if err is not None:
        dur = dur * ~err[:, :, None]
    return dur.sum(-1), jnp.cumsum(dur, -1) - dur


@pytest.mark.parametrize("with_fail", [False, True])
@pytest.mark.parametrize("with_err", [False, True])
def test_kernel_matches_xla_reference(with_fail, with_err):
    rng = np.random.default_rng(0)
    n, b, p = 13, 37, 5  # deliberately unaligned: exercises padding
    base = jnp.asarray(rng.uniform(0, 1, (b, p)).astype(np.float32))
    mask = jnp.asarray(
        (rng.uniform(0, 1, (b, p)) > 0.3).astype(np.float32)
    )
    agg = jnp.asarray(rng.uniform(0, 2, (n, b, p)).astype(np.float32))
    fail = (
        jnp.asarray(rng.integers(0, p + 1, (n, b)).astype(np.int32))
        if with_fail
        else None
    )
    err = (
        jnp.asarray(rng.uniform(0, 1, (n, b)) > 0.7)
        if with_err
        else None
    )
    busy, excl = census_pallas.census(
        base, mask, agg, fail, err, interpret=True
    )
    rb, re = _reference(base, mask, agg, fail, err)
    np.testing.assert_array_equal(np.asarray(busy), np.asarray(rb))
    np.testing.assert_array_equal(np.asarray(excl), np.asarray(re))


def test_bf16_mask_packing_is_exact():
    """0/1 masks are exact in bf16, so the packed-mask kernel is
    bit-equal to the f32-mask reference — the packed_carries pin."""
    rng = np.random.default_rng(1)
    n, b, p = 8, 16, 7
    base = jnp.asarray(rng.uniform(0, 1, (b, p)).astype(np.float32))
    mask_f32 = jnp.asarray(
        (rng.uniform(0, 1, (b, p)) > 0.5).astype(np.float32)
    )
    mask_bf16 = census_pallas.pack_mask(mask_f32)
    assert mask_bf16.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(mask_bf16.astype(jnp.float32)),
        np.asarray(mask_f32),
    )
    agg = jnp.asarray(rng.uniform(0, 2, (n, b, p)).astype(np.float32))
    b1, e1 = census_pallas.census(
        base, mask_f32, agg, interpret=True
    )
    b2, e2 = census_pallas.census(
        base, mask_bf16, agg, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def test_supported_bounds_grid():
    assert census_pallas.supported(1024, 16)
    assert not census_pallas.supported(
        census_pallas.MAX_GRID_ELEMS, 2
    )


@pytest.mark.slow
@pytest.mark.slow
def test_engine_pallas_on_matches_off():
    """End to end: pallas_census=True (interpreter on CPU) reproduces
    the op-by-op engine within 1 ULP on floats, exactly on discrete
    fields — across unrolled dense levels with retries/timeouts/error
    rates AND the scan-bucketed path."""
    g = ServiceGraph.from_yaml(YAML)
    for extra in ({}, {"level_bucket_waste": 64.0}):
        off = Simulator(
            compile_graph(g), SimParams(pallas_census=False, **extra)
        )
        on = Simulator(
            compile_graph(g), SimParams(pallas_census=True, **extra)
        )
        if extra:
            from isotope_tpu.sim.levelscan import ScanBucket

            assert any(
                isinstance(s, ScanBucket) for s in on._segments
            )
        r0 = off.run(OPEN, 4096, KEY)
        r1 = on.run(OPEN, 4096, KEY)
        for f in r0._fields:
            a, b = getattr(r0, f), getattr(r1, f)
            if a is None:
                assert b is None
                continue
            a, b = np.asarray(a), np.asarray(b)
            if a.dtype == bool or np.issubdtype(a.dtype, np.integer):
                np.testing.assert_array_equal(a, b, err_msg=f)
            else:
                np.testing.assert_allclose(
                    a, b, rtol=3e-7, atol=1e-12, err_msg=f
                )


def test_engine_pallas_through_tiles():
    """Tiled sparse levels serve their per-tile census from the kernel
    too; flag on vs off agree."""
    skewed = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: hub}, {call: s0}, {call: s1}]
- name: hub
  script:
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - call: w0
  - call: w1
- name: s0
- name: s1
- name: w0
- name: w1
"""
    g = ServiceGraph.from_yaml(skewed)
    off = Simulator(
        compile_graph(g),
        SimParams(sparse_level_elems=1, pallas_census=False),
    )
    on = Simulator(
        compile_graph(g),
        SimParams(sparse_level_elems=1, pallas_census=True),
    )
    assert any(lvl.tiled is not None for lvl in on._levels)
    r0 = off.run(OPEN, 2048, KEY)
    r1 = on.run(OPEN, 2048, KEY)
    np.testing.assert_allclose(
        np.asarray(r0.client_latency), np.asarray(r1.client_latency),
        rtol=3e-7,
    )
    np.testing.assert_array_equal(
        np.asarray(r0.hop_sent), np.asarray(r1.hop_sent)
    )


def test_auto_flag_resolution_off_tpu():
    g = ServiceGraph.from_yaml(YAML)
    sim = Simulator(compile_graph(g), SimParams())
    # CPU backend: auto resolves to off, the kernel module is unloaded
    assert sim._pallas_census is (jax.default_backend() == "tpu")
    if not sim._pallas_census:
        assert sim._census_mod is None
