"""AOT executable cache + persistent compilation cache (compiler/cache.py).

The executable cache shares jitted entry points across Simulator
instances keyed by the engine shape signature; sharing must be exact —
identical shape signature (bucket bounds, block shape, feature flags)
AND identical baked constants — and any bound/flag change must miss.
"""
import os

import jax
import numpy as np

from isotope_tpu.compiler import compile_graph
from isotope_tpu.compiler.cache import (
    array_digest,
    enable_persistent_cache,
    executable_cache,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator

CHAIN = """
services:
- name: a
  isEntrypoint: true
  script:
  - call: b
- name: b
  script:
  - call: c
- name: c
"""

OPEN = LoadModel(kind="open", qps=100.0)
KEY = jax.random.PRNGKey(0)


def _sim(params=SimParams()):
    return Simulator(compile_graph(ServiceGraph.from_yaml(CHAIN)), params)


def test_identical_topologies_share_one_executable():
    s1, s2 = _sim(), _sim()
    assert s1.signature == s2.signature
    f1 = s1._get(64, "open")
    f2 = s2._get(64, "open")
    assert f1 is f2  # one jitted entry point, process-wide
    # and it runs correctly for the second instance
    r = f2(KEY, np.float32(100.0), np.float32(0.0), np.float32(100.0),
           visits_pc=s2._vis_arg(100.0),
           phase_windows=s2._windows_arg(100.0, False))
    assert int(r.hop_events) == 64 * 3


def test_summary_executable_shared_and_block_size_misses():
    s1, s2 = _sim(), _sim()
    f1 = s1._get_summary(64, 2, "open", 0, None)
    f2 = s2._get_summary(64, 2, "open", 0, None)
    assert f1 is f2
    f3 = s2._get_summary(128, 2, "open", 0, None)  # block size change
    assert f3 is not f1


def test_request_shape_misses():
    s1, s2 = _sim(), _sim()
    assert s1._get(64, "open") is not s2._get(128, "open")
    assert s1._get(64, "open") is s2._get(64, "open")


def test_bucket_bound_change_misses():
    # a different waste budget changes the plan bounds => new signature
    s1 = _sim(SimParams(level_bucket_waste=1.6))
    s2 = _sim(SimParams(level_bucket_waste=64.0))
    # same topology — the plans may or may not coincide, but the
    # signature must incorporate the params either way
    assert s1.signature != s2.signature
    assert s1._get(64, "open") is not s2._get(64, "open")


def test_feature_flag_change_misses():
    s1 = _sim(SimParams())
    s2 = _sim(SimParams(service_time="deterministic"))
    s3 = _sim(SimParams(bucketed_scan=False))
    assert len({s1.signature, s2.signature, s3.signature}) == 3


def test_different_constants_same_shape_miss():
    """Same tensor shapes, different sleep constant: must NOT share."""
    other = CHAIN.replace("- name: c", "- name: c\n  script:\n  - sleep: 1ms")
    s1 = _sim()
    s2 = Simulator(compile_graph(ServiceGraph.from_yaml(other)))
    # shapes differ here (extra step) — craft a pure-constant change:
    g3 = ServiceGraph.from_yaml(CHAIN)
    g3.services[2].num_replicas = 7
    s3 = Simulator(compile_graph(g3))
    assert s1.signature != s2.signature
    assert s1.signature != s3.signature


def test_signature_stable_across_runs():
    s = _sim()
    sig = s.signature
    s.run(OPEN, 64, KEY)
    assert s.signature == sig


def test_array_digest_discriminates():
    a = np.arange(6, dtype=np.float32)
    assert array_digest(a) == array_digest(a.copy())
    assert array_digest(a) != array_digest(a.reshape(2, 3))
    assert array_digest(a) != array_digest(a.astype(np.float64))
    assert array_digest(a, "x") != array_digest(a, "y")
    assert array_digest(None, a) == array_digest(a)


def test_executable_cache_lru_bounds_memory():
    from isotope_tpu.compiler.cache import ExecutableCache

    c = ExecutableCache(max_entries=2)
    c.get_or_build(("a",), lambda: 1)
    c.get_or_build(("b",), lambda: 2)
    c.get_or_build(("a",), lambda: 99)   # hit, refreshes recency
    c.get_or_build(("c",), lambda: 3)    # evicts ("b",)
    assert ("a",) in c and ("c",) in c and ("b",) not in c
    assert c.hits == 1 and c.misses == 3


def test_persistent_cache_env_and_disable(tmp_path, monkeypatch):
    import isotope_tpu.compiler.cache as cache_mod

    monkeypatch.setattr(cache_mod, "_persistent_dir", None)
    monkeypatch.setenv(cache_mod.ENV_CACHE_DIR, "off")
    assert enable_persistent_cache() is None
    d = tmp_path / "xla"
    got = enable_persistent_cache(str(d))
    assert got == str(d) and os.path.isdir(got)
    # idempotent re-enable
    assert enable_persistent_cache(str(d)) == got


def test_persistent_cache_writes_entries(tmp_path, monkeypatch):
    """Compiling through the wired cache leaves entries on disk."""
    import isotope_tpu.compiler.cache as cache_mod

    monkeypatch.setattr(cache_mod, "_persistent_dir", None)
    d = str(tmp_path / "xla")
    enable_persistent_cache(d)
    try:
        sim = _sim(SimParams(cpu_time_s=1.0 / 9_999.0))  # fresh program
        sim.run(OPEN, 32, KEY)
        assert os.listdir(d), "no persistent cache entries written"
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        monkeypatch.setattr(cache_mod, "_persistent_dir", None)


def test_executable_cache_stats_visible():
    executable_cache.clear()
    _sim()._get(48, "open")
    before = executable_cache.hits
    _sim()._get(48, "open")
    assert executable_cache.hits == before + 1
