"""Dense-blocked sparse levels (engine._TiledSteps).

Equivalence contract: every TILE runs the dense step-grid ops
restricted to its rows, so a fully-tiled level is **bit-for-bit
identical to the dense grid in eager** (and <= 1 f32 ULP under jit —
XLA fuses the two program shapes differently); the residual part keeps
the sparse call-slot encoding and inherits its existing ~1 ULP-vs-
dense contract.  The tiling decision itself lives in
compiler/buckets.level_encoding and is shared with the vet linter.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.compiler.buckets import (
    DEFAULT_TILE_PMAX,
    level_encoding,
    plan_tiles,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import OPEN_LOOP, ChaosEvent

KEY = jax.random.PRNGKey(7)
LOAD = LoadModel(kind="open", qps=0.4 / SimParams().cpu_time_s)

# the skewed-level shape: one long mixed script among short/leaf
# siblings at the same depth (tests/test_sparse.py's fixture)
SKEWED = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: hub}, {call: s0}, {call: s1}, {call: s2}]
- name: hub
  script:
  - sleep: 1ms
  - call: w0
  - sleep: 2ms
  - call: w1
  - call: w2
  - sleep: 3ms
  - call: w3
- name: s0
- name: s1
- name: s2
- name: w0
  script: [{sleep: 5ms}]
- name: w1
- name: w2
  script: [{sleep: 1ms}]
- name: w3
"""


def _sims(yaml_text, chaos=(), tile_pmax=DEFAULT_TILE_PMAX, **kw):
    g = ServiceGraph.from_yaml(yaml_text)
    dense = Simulator(compile_graph(g), SimParams(**kw), chaos)
    tiled = Simulator(
        compile_graph(g),
        SimParams(
            sparse_level_elems=1, sparse_tile_pmax=tile_pmax, **kw
        ),
        chaos,
    )
    sparse = Simulator(
        compile_graph(g),
        SimParams(sparse_level_elems=1, sparse_tiling=False, **kw),
        chaos,
    )
    assert all(lvl.tiled is None for lvl in dense._levels)
    assert any(lvl.tiled is not None for lvl in tiled._levels)
    assert any(lvl.sparse is not None for lvl in sparse._levels)
    return dense, tiled, sparse


def _assert_jit_close(ra, rb, rtol):
    for f in ra._fields:
        a, b = getattr(ra, f), getattr(rb, f)
        if a is None or b is None:
            assert a is None and b is None, f
            continue
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype == bool or np.issubdtype(a.dtype, np.integer):
            np.testing.assert_array_equal(a, b, err_msg=f)
        else:
            np.testing.assert_allclose(
                a, b, rtol=rtol, atol=1e-9, err_msg=f
            )


def _assert_eager_bitwise(sim_a, sim_b, n=512):
    args = (KEY, jnp.float32(LOAD.qps), jnp.float32(0.0),
            jnp.float32(LOAD.qps))
    ra = sim_a._simulate(n, OPEN_LOOP, 0, False, *args)
    rb = sim_b._simulate(n, OPEN_LOOP, 0, False, *args)
    for f in ra._fields:
        a, b = getattr(ra, f), getattr(rb, f)
        if a is None or b is None:
            assert a is None and b is None, f
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"eager {f}"
        )


def _check(yaml_text, chaos=(), n=20_000, tile_pmax=DEFAULT_TILE_PMAX,
           eager_bitwise=True, **kw):
    dense, tiled, sparse = _sims(
        yaml_text, chaos=chaos, tile_pmax=tile_pmax, **kw
    )
    rd = dense.run(LOAD, n, KEY)
    rt = tiled.run(LOAD, n, KEY)
    rs = sparse.run(LOAD, n, KEY)
    _assert_jit_close(rd, rt, rtol=3e-7)   # dense vs tiled: ~1 ULP
    _assert_jit_close(rt, rs, rtol=1e-5)   # tiled vs sparse encoding
    if eager_bitwise:
        _assert_eager_bitwise(dense, tiled)
    return dense, tiled, sparse


@pytest.mark.slow
def test_tiled_matches_dense_bitwise_eager():
    _check(SKEWED)


def test_tiled_with_error_rates():
    _check(
        SKEWED.replace(
            "- name: hub\n", "- name: hub\n  errorRate: 30%\n"
        ).replace("- name: w1\n", "- name: w1\n  errorRate: 20%\n")
    )


def test_tiled_with_send_probability():
    _check(
        SKEWED.replace(
            "  - call: w1\n",
            "  - call: {service: w1, probability: 60}\n",
        )
    )


@pytest.mark.slow
def test_tiled_with_retries():
    _check(
        SKEWED.replace(
            "  - call: w3\n",
            "  - call: {service: w3, retries: 2}\n",
        ).replace("- name: w3\n", "- name: w3\n  errorRate: 40%\n")
    )


def test_tiled_with_firing_timeouts():
    dense, _, _ = _check(
        SKEWED.replace(
            "  - call: w0\n",
            "  - call: {service: w0, timeout: 3ms}\n",
        )
    )
    # the truncation genuinely fires (same evidence as the sparse pin)
    rd = dense.run(LOAD, 20_000, KEY)
    assert np.asarray(rd.hop_error)[:, 1].all()
    sent = np.asarray(rd.hop_sent)
    assert sent[:, 5].all() and not sent[:, 6:9].any()


def test_tiled_with_timeout_retries():
    _check(
        SKEWED.replace(
            "  - call: w1\n",
            "  - call: {service: w1, timeout: 0.2ms, retries: 2}\n",
        )
    )


def test_tiled_concurrent_shared_slot_timeout():
    _check(
        SKEWED.replace(
            "  - call: w1\n  - call: w2\n",
            "  - [{call: {service: w1, timeout: 0.1ms}}, {call: w2}]\n",
        )
    )


def test_tiled_with_chaos_total():
    n = 20_000
    dur = n / LOAD.qps
    _check(
        SKEWED,
        chaos=(
            ChaosEvent(
                service="w2",
                start_s=0.25 * dur,
                end_s=0.75 * dur,
                replicas_down=None,
            ),
        ),
        n=n,
    )


def test_residual_sparse_engages_past_tile_cap():
    """A tile cap below the hub's width forces the hub onto the
    residual sparse path; tiles + residual still match dense to the
    sparse contract's tolerance (the residual's cumsum ordering is
    the sparse encoding's, not the dense grid's)."""
    dense, tiled, _ = _sims(SKEWED, tile_pmax=4)
    tl = [lvl.tiled for lvl in tiled._levels if lvl.tiled is not None]
    assert tl and tl[0].residual is not None
    assert len(tl[0].res_hops) == 1  # the hub
    rd = dense.run(LOAD, 20_000, KEY)
    rt = tiled.run(LOAD, 20_000, KEY)
    _assert_jit_close(rd, rt, rtol=1e-5)


def test_residual_with_firing_timeout():
    dense, tiled, sparse = _sims(
        SKEWED.replace(
            "  - call: w0\n",
            "  - call: {service: w0, timeout: 3ms}\n",
        ),
        tile_pmax=4,
    )
    assert any(
        lvl.tiled is not None and lvl.tiled.residual is not None
        for lvl in tiled._levels
    )
    rd = dense.run(LOAD, 20_000, KEY)
    rt = tiled.run(LOAD, 20_000, KEY)
    rs = sparse.run(LOAD, 20_000, KEY)
    _assert_jit_close(rd, rt, rtol=1e-5)
    _assert_jit_close(rt, rs, rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(rd.hop_sent), np.asarray(rt.hop_sent)
    )


def test_callfree_wide_hop_in_residual():
    """A pure-sleep script wider than the tile cap lands in the
    residual with ZERO call slots; with a firing timeout elsewhere in
    the level (transport machinery armed level-wide) the static-busy
    guard must hold and match the dense grid."""
    yaml_text = """
services:
- name: entry
  isEntrypoint: true
  script:
  - [{call: hub}, {call: slow}, {call: s0}, {call: s1}, {call: s2},
     {call: s3}, {call: s4}]
- name: hub
  script:
  - sleep: 1ms
  - call: {service: w0, timeout: 3ms}
  - call: w1
- name: slow
  script:
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
  - sleep: 1ms
- name: s0
- name: s1
- name: s2
- name: s3
- name: s4
- name: w0
  script: [{sleep: 5ms}]
- name: w1
"""
    dense, tiled, sparse = _sims(yaml_text, tile_pmax=3)
    tl = [lvl.tiled for lvl in tiled._levels if lvl.tiled is not None]
    assert tl and tl[0].residual is not None
    assert tl[0].residual.n_slots == 0  # the pure-sleep 'slow' hop
    rd = dense.run(LOAD, 8_192, KEY)
    rt = tiled.run(LOAD, 8_192, KEY)
    _assert_jit_close(rd, rt, rtol=1e-5)
    # the hub's timeout genuinely fires while 'slow' still runs whole
    assert np.asarray(rd.hop_error)[:, 1].all()


def test_deterministic_exact_latency_through_tiles():
    """Quiet-load deterministic run: the tiled hub's latency is the
    exact sum of its steps (the sparse fixture's arithmetic pin)."""
    g = ServiceGraph.from_yaml(SKEWED)
    p = SimParams(
        sparse_level_elems=1, service_time="deterministic"
    )
    sim = Simulator(compile_graph(g), p)
    assert any(lvl.tiled is not None for lvl in sim._levels)
    res = sim.run(LoadModel(kind="open", qps=0.001), 8, KEY)
    cpu = p.cpu_time_s
    net = p.network.one_way(0.0)
    hub = (
        0.001 + 0.002 + 0.003
        + (2 * net + cpu + 0.005)
        + (2 * net + cpu)
        + (2 * net + cpu + 0.001)
        + (2 * net + cpu)
        + cpu
    )
    total = 2 * net + cpu + max(2 * net + hub, 2 * net + cpu)
    np.testing.assert_allclose(
        np.asarray(res.client_latency), total, rtol=1e-5
    )


def test_summary_scan_path_through_tiles():
    _, tiled, sparse = _sims(SKEWED)
    s1 = tiled.run_summary(LOAD, 4096, KEY, block_size=1024)
    s2 = sparse.run_summary(LOAD, 4096, KEY, block_size=1024)
    assert float(s1.count) == float(s2.count)
    assert float(s1.hop_events) == float(s2.hop_events)
    assert float(s1.error_count) == float(s2.error_count)
    np.testing.assert_allclose(
        float(s1.latency_sum), float(s2.latency_sum), rtol=1e-6
    )


@pytest.mark.slow
def test_attribution_oblivious_to_tiling():
    """The blame sweep reads only assembled (N, H) outputs, so an
    attributed tiled run reproduces the sparse engine's blame."""
    g = ServiceGraph.from_yaml(SKEWED)
    pt = SimParams(sparse_level_elems=1, attribution=True)
    ps = dataclasses.replace(pt, sparse_tiling=False)
    st = Simulator(compile_graph(g), pt)
    ss = Simulator(compile_graph(g), ps)
    assert any(lvl.tiled is not None for lvl in st._levels)
    _, at = st.run_attributed(LOAD, 2048, KEY, block_size=512)
    _, as_ = ss.run_attributed(LOAD, 2048, KEY, block_size=512)
    assert float(at.count) == float(as_.count)
    np.testing.assert_allclose(
        np.asarray(at.wait_blame, np.float64),
        np.asarray(as_.wait_blame, np.float64),
        rtol=1e-5, atol=1e-9,
    )
    assert float(at.residual_abs) / float(at.count) < 1e-6


# ---------------------------------------------------------------------------
# planner unit tests (compiler/buckets.plan_tiles / level_encoding)


def test_plan_tiles_bins_by_width_class():
    widths = np.asarray([1] * 100 + [3] * 10 + [40] * 2 + [2000])
    plan = plan_tiles(widths, cap=64, waste=1.6)
    assert list(plan.residual) == [112]  # the 2000-step hub
    sizes = dict(plan.shapes())
    # the 100 single-step hops tile at width 1 (padding a 1-wide hop
    # to 3 would bust the 1.6x budget across 100 rows)
    assert (100, 1) in plan.shapes()
    assert plan.tiled_elems < 0.2 * len(widths) * 2000
    assert sizes  # non-empty
    covered = sorted(
        np.concatenate([idx for _, idx in plan.tiles]).tolist()
        + list(plan.residual)
    )
    assert covered == list(range(len(widths)))


def test_level_encoding_decision_points():
    widths = np.asarray([1] * 999 + [500])
    # tight grid: stays dense
    enc, _ = level_encoding(
        4, 2, 8, np.asarray([2, 2, 2, 2]),
        sparse_level_elems=262_144,
    )
    assert enc == "dense"
    # skewed + tiling on: tiles
    enc, plan = level_encoding(
        1000, 500, 1499, widths, sparse_level_elems=1,
    )
    assert enc == "tiled" and plan is not None
    assert len(plan.residual) == 1
    # tiling off: the true sparse encoding
    enc, plan = level_encoding(
        1000, 500, 1499, widths, sparse_level_elems=1, tiling=False,
    )
    assert enc == "sparse" and plan is None
    # a single wide mostly-sleep hop: every hop is past the tile cap,
    # tiling saves nothing — the true sparse encoding keeps the level
    enc, plan = level_encoding(
        1, 500, 10, np.asarray([500]), sparse_level_elems=1,
    )
    assert enc == "sparse" and plan is None
