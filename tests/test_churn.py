"""Config churner: time-varying traffic splits (config-map.yaml:40-60
rollout.sh parity — VirtualService weight rotation as send-probability
schedules)."""
import jax
import numpy as np
import pytest
import yaml

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.runner.config import load_toml
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import TrafficSplit

CANARY = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: v1
  - call: v2
- name: v1
  script: [{sleep: 1ms}]
- name: v2
  script: [{sleep: 1ms}]
"""

KEY = jax.random.PRNGKey(7)


def sim_with(churn, doc=CANARY, **params):
    g = ServiceGraph.decode(yaml.safe_load(doc))
    return Simulator(compile_graph(g), SimParams(**params), churn=churn)


def hop_fraction(res, compiled, service):
    """Fraction of requests that actually hit ``service``."""
    svc = list(compiled.services.names).index(service)
    cols = np.asarray(compiled.hop_service) == svc
    sent = np.asarray(res.hop_sent)[:, cols].any(axis=1)
    return sent, np.asarray(res.client_start)


def test_square_wave_split_follows_schedule():
    # v1 on for the first second of every 2s cycle, off for the second
    churn = (TrafficSplit(service="v1", period_s=1.0,
                          weights=(1.0, 0.0)),)
    sim = sim_with(churn)
    res = sim.run(LoadModel(kind="open", qps=500.0), 4000, KEY)
    sent, starts = hop_fraction(res, sim.compiled, "v1")
    phase = np.floor(starts).astype(int) % 2
    assert sent[phase == 0].mean() == pytest.approx(1.0)
    assert sent[phase == 1].mean() == pytest.approx(0.0)
    # v2 is not churned: always called
    sent2, _ = hop_fraction(res, sim.compiled, "v2")
    assert sent2.all()


def test_canary_rotation_mean_traffic():
    # the reference's canary weights 100/70/40/20 over the cycle
    churn = (
        TrafficSplit(service="v1", period_s=0.5,
                     weights=(1.0, 0.7, 0.4, 0.2)),
        TrafficSplit(service="v2", period_s=0.5,
                     weights=(0.0, 0.3, 0.6, 0.8)),
    )
    sim = sim_with(churn)
    res = sim.run(LoadModel(kind="open", qps=2000.0), 20000, KEY)
    sent1, _ = hop_fraction(res, sim.compiled, "v1")
    sent2, _ = hop_fraction(res, sim.compiled, "v2")
    assert sent1.mean() == pytest.approx(np.mean([1.0, 0.7, 0.4, 0.2]),
                                         abs=0.03)
    assert sent2.mean() == pytest.approx(np.mean([0.0, 0.3, 0.6, 0.8]),
                                         abs=0.03)


def test_churn_scales_offered_load_and_subtree():
    # churning a mid service scales its whole subtree's utilization
    doc = """
services:
- name: entry
  isEntrypoint: true
  script: [{call: mid}]
- name: mid
  script: [{call: leaf}]
- name: leaf
"""
    churn = (TrafficSplit(service="mid", period_s=1.0,
                          weights=(0.5,)),)
    base = sim_with((), doc=doc)
    split = sim_with(churn, doc=doc)
    v_base = np.asarray(base._visits)
    v_split = np.asarray(split._visits)
    names = list(base.compiled.services.names)
    for svc in ("mid", "leaf"):
        i = names.index(svc)
        assert v_split[i] == pytest.approx(0.5 * v_base[i])
    assert v_split[names.index("entry")] == v_base[names.index("entry")]


def test_churn_through_scan_path_continuous_timeline():
    # blocks must see one continuous clock: with 1s on / 1s off at
    # 500 qps and 1024-request blocks (~2s each), a restarted clock
    # would put every block's requests in the "on" phase
    churn = (TrafficSplit(service="v1", period_s=1.0,
                          weights=(1.0, 0.0)),)
    sim = sim_with(churn)
    s = sim.run_summary(LoadModel(kind="open", qps=500.0), 4096, KEY,
                        block_size=1024)
    # entry + v2 always run; v1 half the time => 2.5 hops/request
    assert float(s.hop_events) / 4096 == pytest.approx(2.5, abs=0.05)


def test_churn_validation():
    with pytest.raises(ValueError, match="period"):
        TrafficSplit(service="x", period_s=0.0, weights=(1.0,))
    with pytest.raises(ValueError, match="weights"):
        TrafficSplit(service="x", period_s=1.0, weights=())
    with pytest.raises(ValueError, match="weights"):
        TrafficSplit(service="x", period_s=1.0, weights=(1.5,))
    with pytest.raises(ValueError, match="unknown service"):
        sim_with((TrafficSplit(service="nosuch", period_s=1.0,
                               weights=(1.0,)),))
    with pytest.raises(ValueError, match="multiple traffic splits"):
        sim_with(
            (
                TrafficSplit(service="v1", period_s=1.0, weights=(1.0,)),
                TrafficSplit(service="v1", period_s=2.0, weights=(0.5,)),
            )
        )
    # churning the entrypoint would be a silent no-op: reject it
    with pytest.raises(ValueError, match="no callable edge"):
        sim_with((TrafficSplit(service="entry", period_s=1.0,
                               weights=(0.5,)),))


def test_churn_toml_plumbing(tmp_path):
    topo = tmp_path / "t.yaml"
    topo.write_text(CANARY)
    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [100]
load_kind = "open"

[[churn]]
service = "v1"
period = "30s"
weights = [1.0, 0.7, 0.4, 0.2]
"""
    )
    config = load_toml(cfg)
    assert len(config.churn) == 1
    assert config.churn[0].service == "v1"
    assert config.churn[0].period_s == 30.0
    assert config.churn[0].weights == (1.0, 0.7, 0.4, 0.2)


def test_churn_queueing_sees_per_phase_load():
    # a square-wave split at near-capacity load: the ON phase must show
    # the heavy-traffic waits, not the time-averaged (stable) ones
    doc = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: hot, probability: 100}
- name: hot
"""
    from isotope_tpu.sim.config import SimParams

    mu = 1.0 / SimParams().cpu_time_s
    churn = (TrafficSplit(service="hot", period_s=2.0,
                          weights=(1.0, 0.0)),)
    sim = sim_with(churn, doc=doc)
    # offered 0.9*mu while ON; time-average only 0.45*mu
    res = sim.run(LoadModel(kind="open", qps=0.9 * mu), 60000, KEY)
    sent, starts = hop_fraction(res, sim.compiled, "hot")
    lat = np.asarray(res.client_latency)
    phase = np.floor(starts / 2.0).astype(int) % 2
    on = lat[(phase == 0) & sent]
    # ON-phase waits must match an unchurned run at the SAME rate
    base = sim_with((), doc=doc)
    res_b = base.run(LoadModel(kind="open", qps=0.9 * mu), 60000,
                     jax.random.fold_in(KEY, 1))
    lat_b = np.asarray(res_b.client_latency)
    assert np.mean(on) == pytest.approx(np.mean(lat_b), rel=0.05)
    # and they are far above what the 0.45*mu average would predict
    avg_sim = sim_with((), doc=doc)
    res_a = avg_sim.run(LoadModel(kind="open", qps=0.45 * mu), 60000,
                        jax.random.fold_in(KEY, 2))
    assert np.mean(on) > 1.5 * np.mean(np.asarray(res_a.client_latency))
