"""Alarm-suite tests (check_metrics.py / metrics/prometheus.py parity).

The alarms evaluate real query strings against the run's own text
exposition — the same consumption path a Prometheus scraper + PromQL
would take against the reference's cluster.
"""
import jax
import pytest

from isotope_tpu import cli
from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.alarms import (
    Alarm,
    Query,
    requests_sanity,
    run_queries,
    standard_queries,
    store_from_summary,
)
from isotope_tpu.metrics.prometheus import MetricsCollector
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator

KEY = jax.random.PRNGKey(2)


def store(yaml, qps=100.0, n=5000, **simkw):
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    collector = MetricsCollector(compiled)
    summary = Simulator(compiled, SimParams(**simkw)).run_summary(
        LoadModel(kind="open", qps=qps), n, KEY, collector=collector
    )
    return store_from_summary(collector, summary)


CLEAN = "services:\n- name: a\n  isEntrypoint: true\n  responseSize: 1KiB\n"


def test_clean_run_passes_standard_queries():
    s = store(CLEAN)
    errors = run_queries(standard_queries() + [requests_sanity()], s)
    assert errors == []


def test_5xx_alarm_fires_on_error_rate():
    s = store(
        "services:\n- name: a\n  isEntrypoint: true\n  errorRate: 10%\n"
    )
    errors = run_queries(standard_queries(), s)
    assert any("5xx" in e for e in errors)


def test_cpu_alarm_fires_under_heavy_load():
    # one replica near saturation: ~0.9 cores >> the 50m default limit
    s = store(CLEAN, qps=0.9 / SimParams().cpu_time_s, n=20000)
    errors = run_queries(standard_queries(), s)
    assert any("CPU" in e for e in errors)
    # the load-test override (250m) still fires at 900m
    errors = run_queries(standard_queries(cpu_lim=250), s)
    assert any("CPU" in e for e in errors)
    # a generous limit does not
    errors = run_queries(standard_queries(cpu_lim=2000, mem_lim=1000), s)
    assert errors == []


def test_memory_gauge_positive_and_bounded():
    s = store(CLEAN)
    mem = s.query_value("max(service_memory_working_set_bytes)")
    assert 0 < mem < 1e6  # a few in-flight 1KiB payloads


def test_cpu_query_matches_utilization():
    # 100 qps at ~77us/req => ~7.7 milli-cores
    s = store(CLEAN)
    mcores = s.query_value(
        "max(sum(rate(service_cpu_usage_seconds_total[1m])) "
        "by (service)) * 1000"
    )
    assert mcores == pytest.approx(7.7, rel=0.1)


def test_latency_quantile_over_service_histogram():
    # the reference's prom.py:216-232 consumer shape works against the
    # sim's service_request_duration_seconds histogram
    s = store(CLEAN, qps=500.0, n=20000)
    v = s.query(
        "histogram_quantile(0.99, sum(rate("
        "service_request_duration_seconds_bucket[180s])) "
        "by (service, le)) * 1000"
    )
    (p99_ms,) = v.values()
    # sub-ms service latencies fall in the first 7ms bucket
    assert 0 < p99_ms <= 7.0


def test_running_query_gate_skips():
    s = store(CLEAN)
    q = Query(
        "gated",
        "sum(service_incoming_requests_total)",
        Alarm(lambda v: True, "should be skipped"),
        'sum(service_incoming_requests_total{service="not-deployed"})',
    )
    assert run_queries([q], s) == []


def test_check_cli(tmp_path, capsys):
    topo = tmp_path / "t.yaml"
    topo.write_text(CLEAN)
    rc = cli.main(
        ["check", str(topo), "--qps", "50", "--duration", "60s",
         "--max-requests", "3000"]
    )
    assert rc == 0
    assert "4/4 checks passed" in capsys.readouterr().err

    bad = tmp_path / "bad.yaml"
    bad.write_text(
        "services:\n- name: a\n  isEntrypoint: true\n  errorRate: 5%\n"
    )
    rc = cli.main(
        ["check", str(bad), "--qps", "50", "--duration", "60s",
         "--max-requests", "3000"]
    )
    assert rc == 1
    assert "ALARM" in capsys.readouterr().err
