"""Alarm-suite tests (check_metrics.py / metrics/prometheus.py parity)."""
import jax
import pytest

from isotope_tpu import cli
from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.alarms import (
    Alarm,
    Query,
    RunSource,
    requests_sanity,
    run_queries,
    standard_queries,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator

KEY = jax.random.PRNGKey(2)


def source(yaml, qps=100.0, n=5000, **simkw):
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    res = Simulator(compiled, SimParams(**simkw)).run(
        LoadModel(kind="open", qps=qps), n, KEY
    )
    return RunSource(compiled, res)


CLEAN = "services:\n- name: a\n  isEntrypoint: true\n  responseSize: 1KiB\n"


def test_clean_run_passes_standard_queries():
    s = source(CLEAN)
    errors = run_queries(standard_queries() + [requests_sanity()], s)
    assert errors == []


def test_5xx_alarm_fires_on_error_rate():
    s = source(
        "services:\n- name: a\n  isEntrypoint: true\n  errorRate: 10%\n"
    )
    errors = run_queries(standard_queries(), s)
    assert any("5xx" in e for e in errors)


def test_cpu_alarm_fires_under_heavy_load():
    # one replica near saturation: ~0.9 cores >> the 50m default limit
    s = source(CLEAN, qps=0.9 / SimParams().cpu_time_s, n=20000)
    errors = run_queries(standard_queries(), s)
    assert any("CPU" in e for e in errors)
    # the load-test override (250m) still fires at 900m
    errors = run_queries(standard_queries(cpu_lim=250), s)
    assert any("CPU" in e for e in errors)
    # a generous limit does not
    errors = run_queries(standard_queries(cpu_lim=2000, mem_lim=1000), s)
    assert errors == []


def test_memory_estimate_positive_and_bounded():
    s = source(CLEAN)
    mem = s.max_memory_bytes()
    assert 0 < mem < 1e6  # a few in-flight 1KiB payloads


def test_running_query_gate_skips():
    s = source(CLEAN)
    q = Query(
        "gated", lambda _: 1.0,
        Alarm(lambda v: True, "should be skipped"),
        lambda _: False,
    )
    assert run_queries([q], s) == []


def test_check_cli(tmp_path, capsys):
    topo = tmp_path / "t.yaml"
    topo.write_text(CLEAN)
    rc = cli.main(
        ["check", str(topo), "--qps", "50", "--duration", "60s",
         "--max-requests", "3000"]
    )
    assert rc == 0
    assert "4/4 checks passed" in capsys.readouterr().err

    bad = tmp_path / "bad.yaml"
    bad.write_text(
        "services:\n- name: a\n  isEntrypoint: true\n  errorRate: 5%\n"
    )
    rc = cli.main(
        ["check", str(bad), "--qps", "50", "--duration", "60s",
         "--max-requests", "3000"]
    )
    assert rc == 1
    assert "ALARM" in capsys.readouterr().err
