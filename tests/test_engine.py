"""Simulation engine tests.

Strategy (SURVEY.md §4): deterministic golden values for the tree
semantics (sequential steps sum, concurrent fan-out joins at the max,
sleeps overlap a group's calls), distribution checks against closed-form
M/M/1, and behavioral checks for probability / errorRate — the semantics
the reference's executor implements (or promises) in
isotope/service/pkg/srv/executable.go.
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, NetworkModel, SimParams, Simulator
from isotope_tpu.sim import queueing

KEY = jax.random.PRNGKey(7)

# Deterministic service time + negligible load => queueing waits are
# almost surely zero, so latencies are exact sums/maxes.
DET = SimParams(service_time="deterministic", network=NetworkModel())
QUIET = LoadModel(kind="open", qps=0.001, duration_s=1.0)
CPU = DET.cpu_time_s
RTT1 = 2 * DET.network.base_latency_s  # zero-byte round trip


def run(yaml, n=64, params=DET, load=QUIET, key=KEY):
    sim = Simulator(compile_graph(ServiceGraph.from_yaml(yaml)), params)
    return sim.run(load, n, key)


def test_single_service_golden():
    res = run("services:\n- name: a\n  isEntrypoint: true\n")
    np.testing.assert_allclose(res.client_latency, RTT1 + CPU, rtol=1e-5)
    assert not bool(res.client_error.any())
    assert int(res.hop_events) == 64


def test_sequential_steps_sum():
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - sleep: 10ms
  - call: leaf
  - sleep: 5ms
- name: leaf
"""
    )
    # entry busy = 10ms + (rtt + leaf) + 5ms; leaf = cpu
    want = RTT1 + CPU + 0.010 + (RTT1 + CPU) + 0.005
    np.testing.assert_allclose(res.client_latency, want, rtol=1e-5)


def test_concurrent_fanout_joins_at_max():
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - - call: slow
    - call: fast
    - sleep: 1ms
- name: slow
  script:
  - sleep: 50ms
- name: fast
  script:
  - sleep: 2ms
"""
    )
    # group duration = max(1ms, rtt+fast, rtt+slow) = rtt + cpu + 50ms
    want = RTT1 + CPU + (RTT1 + CPU + 0.050)
    np.testing.assert_allclose(res.client_latency, want, rtol=1e-5)


def test_concurrent_sleep_dominates_when_longest():
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - - sleep: 100ms
    - call: leaf
- name: leaf
"""
    )
    want = RTT1 + CPU + 0.100  # the 100ms sleep outlasts the call
    np.testing.assert_allclose(res.client_latency, want, rtol=1e-5)


def test_call_probability_rate():
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: leaf, probability: 25}
- name: leaf
""",
        n=8000,
    )
    rate = float(res.hop_sent[:, 1].mean())
    assert rate == pytest.approx(0.25, abs=0.02)


def test_error_rate_injects_500_and_skips_script():
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  errorRate: 100%
  script:
  - sleep: 500ms
  - call: leaf
- name: leaf
""",
        n=32,
    )
    assert bool(res.client_error.all())
    # fail-fast: the 500ms sleep is skipped and leaf is never called
    assert int(res.hop_sent[:, 1].sum()) == 0
    np.testing.assert_allclose(res.client_latency, RTT1 + CPU, rtol=1e-5)


def test_downstream_error_does_not_fail_caller():
    # executable.go:132-143: non-200 from a callee is recorded, not
    # propagated — the caller still returns 200.
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: leaf
- name: leaf
  errorRate: 100%
""",
        n=32,
    )
    assert not bool(res.client_error.any())
    assert bool(res.hop_error[:, 1].all())


def test_start_times_respect_causality():
    res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - sleep: 10ms
  - call: mid
- name: mid
  script:
  - call: leaf
- name: leaf
""",
        n=16,
        load=LoadModel(kind="open", qps=100.0),
    )
    start = np.asarray(res.hop_start)
    # entry -> mid: at least the 10ms sleep + network later
    assert (start[:, 1] >= start[:, 0] + 0.010).all()
    # mid -> leaf: one-way wire time later
    assert (start[:, 2] >= start[:, 1] + 2e-4).all()
    # open-loop arrivals are strictly increasing
    assert (np.diff(np.asarray(res.client_start)) > 0).all()


def test_mm1_sojourn_distribution():
    """Single-station latencies must match the closed-form M/M/1 sojourn.

    With exponential service times, wait+service of our Erlang-C sampler
    is exactly Exp(mu - lambda) for k=1 — p50/p99 within a few percent.
    """
    params = SimParams(service_time="exponential")
    mu = 1.0 / params.cpu_time_s
    lam = 0.8 * mu
    res = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=200_000,
        params=params,
        load=LoadModel(kind="open", qps=lam),
    )
    sojourn = np.asarray(res.client_latency) - RTT1
    for q in (0.5, 0.9, 0.99):
        want = float(queueing.mm1_sojourn_quantile(q, lam, mu))
        got = float(np.quantile(sojourn, q))
        assert got == pytest.approx(want, rel=0.05), q
    assert float(res.utilization[0]) == pytest.approx(0.8, rel=1e-3)
    assert not bool(res.unstable[0])


def test_unstable_station_reported():
    res = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        load=LoadModel(kind="open", qps=1e6),
        n=128,
    )
    assert bool(res.unstable[0])
    assert float(res.utilization[0]) > 1.0


def test_closed_loop_throughput_self_throttles():
    # qps=None (fortio -qps max): 4 workers issue back-to-back; at the
    # implied rate queueing kicks in, so compare means, not constants.
    res = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=4096,
        load=LoadModel(kind="closed", qps=None, connections=4),
    )
    lat = np.asarray(res.client_latency)
    starts = np.asarray(res.client_start).reshape(4, 1024)
    gaps = np.diff(starts, axis=1)
    # workers are never idle: gap between consecutive sends == latency
    np.testing.assert_allclose(
        gaps.mean(), lat.reshape(4, 1024)[:, :-1].mean(), rtol=1e-5
    )
    # the fixed point lands near lam = C / E[latency]
    assert float(res.offered_qps) == pytest.approx(
        4 / lat.mean(), rel=0.15
    )


def test_closed_loop_paced_by_qps():
    res = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=1000,
        load=LoadModel(kind="closed", qps=100.0, connections=10),
    )
    # each of 10 workers paces to 10 rps => gaps of 100ms >> latency
    starts = np.asarray(res.client_start).reshape(10, 100)
    np.testing.assert_allclose(np.diff(starts, axis=1), 0.1, rtol=1e-4)


def test_heavy_tail_service_times():
    """Lognormal/Pareto mixtures keep the mean but fatten the tail."""
    import numpy as _np

    base = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=100_000,
        params=SimParams(service_time="exponential"),
    )
    logn = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=100_000,
        params=SimParams(service_time="lognormal", service_time_param=2.0),
    )
    par = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=100_000,
        params=SimParams(service_time="pareto", service_time_param=1.5),
    )
    for res in (logn, par):
        svc = _np.asarray(res.client_latency) - RTT1
        bsvc = _np.asarray(base.client_latency) - RTT1
        # same mean (within MC noise; pareto alpha=1.5 converges slowly)
        assert svc.mean() == pytest.approx(bsvc.mean(), rel=0.25)
        # much fatter p999
        assert _np.quantile(svc, 0.999) > 3 * _np.quantile(bsvc, 0.999)


def test_service_time_param_validation():
    with pytest.raises(ValueError):
        SimParams(service_time="pareto", service_time_param=1.0)
    with pytest.raises(ValueError):
        SimParams(service_time="lognormal", service_time_param=0.0)
    with pytest.raises(ValueError):
        SimParams(service_time="weibull")


def test_closed_loop_remainder_requests_paced():
    """Remainder requests (n % connections) continue on existing
    connections — they must not all start at t=0 (round-1 finding #9)."""
    res = run(
        "services:\n- name: a\n  isEntrypoint: true\n",
        n=1003,  # 10 conns x 100 + 3 remainder
        load=LoadModel(kind="closed", qps=100.0, connections=10),
    )
    starts = np.asarray(res.client_start)
    rem = starts[1000:]
    # each remainder request starts when its connection frees up (~10s in)
    assert (rem > 9.0).all(), rem
    # ActualQPS over the whole run stays within 2% of the pacing target
    total = float(np.asarray(res.client_end).max())
    assert 1003 / total == pytest.approx(100.0, rel=0.02)
