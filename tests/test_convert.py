"""Converter (kubernetes + graphviz) tests.

Mirrors the reference's graphviz golden test and the manifest generator's
structure (kubernetes.go:56-137).
"""
import yaml

from isotope_tpu.convert import graphviz as gv
from isotope_tpu.convert import kubernetes as k8s
from isotope_tpu.models.graph import ServiceGraph

CANONICAL = "examples/topologies/canonical.yaml"


def _manifests(environment="NONE"):
    with open(CANONICAL) as f:
        text = f.read()
    graph = ServiceGraph.from_yaml(text)
    opts = k8s.ConvertOptions(environment_name=environment)
    return graph, k8s.service_graph_to_manifests(graph, text, opts)


def test_manifest_kinds_and_counts():
    graph, manifests = _manifests()
    kinds = [m["kind"] for m in manifests]
    # Namespace + ConfigMap + 4x(Service+Deployment) + fortio client
    # Deployment+Service (kubernetes.go:56-137, fortio_client.go:28-78).
    assert kinds.count("Namespace") == 1
    assert kinds.count("ConfigMap") == 1
    assert kinds.count("Service") == 4 + 1
    assert kinds.count("Deployment") == 4 + 1


def test_namespace_istio_injection():
    _, manifests = _manifests()
    ns = manifests[0]
    assert ns["metadata"]["labels"] == {"istio-injection": "enabled"}


def test_config_map_embeds_topology():
    graph, manifests = _manifests()
    cm = manifests[1]
    embedded = yaml.safe_load(cm["data"]["service-graph.yaml"])
    assert ServiceGraph.decode(embedded).service_names() == graph.service_names()


def test_deployment_env_and_mount():
    _, manifests = _manifests()
    dep = next(
        m
        for m in manifests
        if m["kind"] == "Deployment" and m["metadata"]["name"] == "a"
    )
    container = dep["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"] for e in container["env"]}
    assert {"SERVICE_NAME", "PODNAME", "PODIP", "NAMESPACE", "NODENAME"} <= env
    assert container["volumeMounts"][0]["mountPath"] == "/etc/config"
    annotations = dep["spec"]["template"]["metadata"]["annotations"]
    assert annotations["prometheus.io/scrape"] == "true"


def test_rbac_only_for_istio():
    _, none_manifests = _manifests("NONE")
    _, istio_manifests = _manifests("ISTIO")
    assert not any(m["kind"] == "ServiceRole" for m in none_manifests)
    roles = [m for m in istio_manifests if m["kind"] == "ServiceRole"]
    # canonical.yaml: numRbacPolicies 3 via defaults, 4 services.
    assert len(roles) == 12
    assert any(m["kind"] == "RbacConfig" for m in istio_manifests)


def test_manifests_yaml_parses():
    _, manifests = _manifests()
    docs = list(yaml.safe_load_all(k8s.manifests_to_yaml(manifests)))
    assert len(docs) == len(manifests)


def test_dot_output():
    graph = ServiceGraph.from_yaml_file(CANONICAL)
    dot = gv.to_dot(graph)
    assert dot.startswith("digraph {")
    # every service gets a node; every call gets an edge from its step port
    for name in "abcd":
        assert f'"{name}"' in dot
    assert '"d":s0 -> "a";' in dot
    assert '"d":s0 -> "c";' in dot
    assert '"d":s1 -> "b";' in dot
    assert '"c":s0 -> "a";' in dot
    assert '"c":s1 -> "b";' in dot


def test_dot_escapes_quoted_node_ids():
    from isotope_tpu.convert.graphviz import to_dot
    from isotope_tpu.models.graph import ServiceGraph

    g = ServiceGraph.decode(
        {"services": [{"name": 'a"b'}, {"name": "c", "script": [{"call": 'a"b'}]}]}
    )
    dot = to_dot(g)
    assert '"a\\"b"' in dot
    assert '-> "a\\"b";' in dot
