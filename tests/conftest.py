"""Test configuration: force an 8-device virtual CPU mesh.

All sharding tests run against ``jax.sharding.Mesh`` over 8 virtual CPU
devices so multi-chip paths are exercised without TPU hardware (the driver
separately dry-runs ``__graft_entry__.dryrun_multichip``).

Note: the ambient environment preimports jax at interpreter startup (the
axon sitecustomize) with ``JAX_PLATFORMS=axon``, so environment variables
set here are read too late — only ``jax.config.update`` works.
"""
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
