"""Test configuration: force an 8-device virtual CPU mesh.

All sharding tests run against ``jax.sharding.Mesh`` over 8 virtual CPU
devices so multi-chip paths are exercised without TPU hardware (the driver
separately dry-runs ``__graft_entry__.dryrun_multichip``).

Note: the ambient environment preimports jax at interpreter startup (the
axon sitecustomize) with ``JAX_PLATFORMS=axon``, so environment variables
set here are read too late — only ``jax.config.update`` works.  Older
jax releases (< 0.5) have no ``jax_num_cpu_devices`` option; there the
device count comes from ``XLA_FLAGS``, which IS still honored as long
as no backend has initialized (preimporting jax does not initialize
one), so set it before the first ``jax.devices()`` call.
"""
import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
