"""Test configuration: force an 8-device virtual CPU mesh.

All sharding tests run against ``jax.sharding.Mesh`` over 8 virtual CPU
devices so multi-chip paths are exercised without TPU hardware (the driver
separately dry-runs ``__graft_entry__.dryrun_multichip``).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
