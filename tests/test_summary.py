"""Microbatched (lax.scan) summary path vs the direct per-request path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.histogram import quantile_from_histogram
from isotope_tpu.metrics.prometheus import MetricsCollector
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import ChaosEvent, LoadModel
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.metrics.histogram import latency_histogram

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: mid
- name: mid
  script:
  - call: leaf
- name: leaf
  script:
  - sleep: 1ms
"""


# chaos tests kill the direct callee of the entrypoint: a transport error
# fails only its direct caller (executable.go:132-143) — deeper chains
# surface as downstream 500s the client never sees
CHAIN2 = """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: mid
- name: mid
  script:
  - sleep: 1ms
"""


def _sim(chaos=(), doc=CHAIN):
    g = ServiceGraph.decode(yaml.safe_load(doc))
    return Simulator(compile_graph(g), chaos=chaos)


def test_open_loop_blocks_match_direct_run():
    sim = _sim()
    key = jax.random.PRNGKey(0)
    load = LoadModel(kind="open", qps=500.0)
    n = 4096
    s = sim.run_summary(load, n, key, block_size=1024)
    assert float(s.count) == n
    assert float(s.hop_events) == n * 3
    assert float(s.error_count) == 0

    res = sim.run(load, n, key)
    direct_mean = float(res.client_latency.mean())
    assert s.mean_latency_s == pytest.approx(direct_mean, rel=0.05)
    p50_direct = float(jnp.quantile(res.client_latency, 0.5))
    p50_blocks = s.quantiles_s([0.5])[0]
    assert p50_blocks == pytest.approx(p50_direct, rel=0.05)


def test_single_block_is_exact_equal_to_direct():
    # one block, same key path (fold_in(key, 0) vs direct) will differ in
    # RNG, but block math must produce identical statistics structure:
    # count/hops exact, histogram sums to count
    sim = _sim()
    s = sim.run_summary(
        LoadModel(kind="open", qps=500.0), 1000, jax.random.PRNGKey(1),
        block_size=1000,
    )
    assert float(s.count) == 1000
    assert float(np.asarray(s.latency_hist).sum()) == 1000


def test_open_loop_timeline_continues_across_blocks():
    # chaos kills the leaf for t in [2, 4): with 500 qps and 4096 requests
    # the run spans ~8.2s, so ~25% of requests see transport errors.  If
    # blocks each restarted at t=0, every block would put ~25% in the
    # window; if t0 did NOT carry, a 1024-request block spans only ~2.05s
    # and the window [2,4) would be hit by almost no requests after block
    # 0 -> error fraction far below 20%.
    chaos = (ChaosEvent(service="mid", start_s=2.0, end_s=4.0),)
    sim = _sim(chaos=chaos, doc=CHAIN2)
    load = LoadModel(kind="open", qps=500.0)
    n = 4096
    s = sim.run_summary(load, n, jax.random.PRNGKey(2), block_size=1024)
    frac = float(s.error_count) / n
    assert 0.15 < frac < 0.35

    res = sim.run(load, n, jax.random.PRNGKey(2))
    frac_direct = float(res.client_error.mean())
    assert frac == pytest.approx(frac_direct, abs=0.05)


def test_closed_loop_blocks_and_connection_clock_carry():
    sim = _sim()
    load = LoadModel(kind="closed", qps=None, connections=8)
    n = 2048
    s = sim.run_summary(load, n, jax.random.PRNGKey(3), block_size=512)
    assert float(s.count) >= n
    res = sim.run(load, n, jax.random.PRNGKey(3))
    assert s.mean_latency_s == pytest.approx(
        float(res.client_latency.mean()), rel=0.05
    )


def test_closed_loop_max_qps_chaos_phases_are_hit():
    # ADVICE r1 (medium): closed-loop qps=None used pace_gap=0 for phase
    # placement, so every request landed in phase 0 and chaos never fired.
    chaos = (ChaosEvent(service="mid", start_s=0.5, end_s=1e9),)
    sim = _sim(chaos=chaos, doc=CHAIN2)
    load = LoadModel(kind="closed", qps=None, connections=4)
    res = sim.run(load, 4096, jax.random.PRNGKey(4))
    # nearly all requests arrive after 0.5s => transport errors dominate
    assert float(res.client_error.mean()) > 0.5


@pytest.mark.slow
@pytest.mark.slow
def test_metrics_accumulate_across_blocks():
    sim = _sim()
    collector = MetricsCollector(sim.compiled)
    s = sim.run_summary(
        LoadModel(kind="open", qps=500.0), 3000, jax.random.PRNGKey(5),
        block_size=1024,
    )
    assert s.metrics is None
    s = sim.run_summary(
        LoadModel(kind="open", qps=500.0), 3000, jax.random.PRNGKey(5),
        block_size=1024, collector=collector,
    )
    inc = np.asarray(s.metrics.incoming_total)
    # 3 blocks of 1024
    assert inc.sum() == 3 * 3072
    assert (inc == 3072).all()


def test_histogram_quantiles_from_merged_blocks():
    # merged histogram quantiles track the true sample quantiles
    rng = np.random.default_rng(0)
    samples = rng.lognormal(-6.0, 0.5, 20000).astype(np.float32)
    h1 = latency_histogram(jnp.asarray(samples[:10000]))
    h2 = latency_histogram(jnp.asarray(samples[10000:]))
    merged = np.asarray(h1) + np.asarray(h2)
    got = quantile_from_histogram(merged, [0.5, 0.99])
    want = np.quantile(samples, [0.5, 0.99])
    np.testing.assert_allclose(got, want, rtol=0.02)
