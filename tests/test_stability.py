"""Stability-scenario analogues: gateway-bouncer and graceful-shutdown.

The reference's stability suite includes two scenarios with clean
simulation analogues (SURVEY.md §2.3 #28):

- **gateway-bouncer** (perf/stability/gateway-bouncer/README.md:14-21):
  the ingress gateway is rolling-restarted on a loop; fortio clients
  crash on the connection errors each bounce causes.  Analogue:
  ``bounce_schedule`` pointed at the entrypoint — repeated total-outage
  windows during which the entry refuses connections.
- **graceful-shutdown** (perf/stability/graceful-shutdown/): a long
  in-flight request across a replica kill.  Analogue:
  ``ChaosEvent(drain=...)`` — graceful kills only remove capacity
  (in-flight requests complete); ungraceful kills reset the requests
  resident on the killed replicas (transport errors at the client).
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import ChaosEvent, bounce_schedule
from isotope_tpu.sim.oracle import OracleSimulator

KEY = jax.random.PRNGKey(11)
MU = 1.0 / SimParams().cpu_time_s

LONG_REQUEST = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script: [{call: worker}]
- name: worker
  numReplicas: 4
  script: [{sleep: 2s}]
"""

SIMPLE = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 2
"""


def test_bounce_schedule_windows():
    evs = bounce_schedule("gw", period_s=60.0, down_s=5.0, count=3,
                          start_s=10.0)
    assert [(e.start_s, e.end_s) for e in evs] == [
        (10.0, 15.0), (70.0, 75.0), (130.0, 135.0)
    ]
    assert all(e.replicas_down is None and e.drain for e in evs)
    with pytest.raises(ValueError, match="down_s"):
        bounce_schedule("gw", period_s=5.0, down_s=6.0, count=1)


def test_gateway_bouncer_errors_only_in_bounce_windows():
    # rolling entry restarts: connection errors DURING each bounce
    # window, clean traffic outside — the detector the reference's
    # fortio clients implement by crashing on errors
    graph = ServiceGraph.from_yaml(SIMPLE)
    chaos = bounce_schedule("entry", period_s=10.0, down_s=2.0, count=4,
                            start_s=5.0)
    engine = Simulator(compile_graph(graph), SimParams(), chaos)
    load = LoadModel(kind="open", qps=2000.0)
    res = engine.run(load, 80_000, KEY)
    st = np.asarray(res.client_start)
    err = np.asarray(res.client_error)
    in_bounce = np.zeros_like(err)
    for ev in chaos:
        in_bounce |= (st >= ev.start_s) & (st < ev.end_s)
    # all bounce-window requests are refused; all others succeed
    assert err[in_bounce].all()
    assert not err[~in_bounce].any()
    # refused connections cost one wire round trip, not a full request
    lat = np.asarray(res.client_latency)
    assert lat[in_bounce].max() < lat[~in_bounce].min()

    # the oracle agrees on the error fraction
    oracle = OracleSimulator(graph, SimParams(), chaos)
    ro = oracle.run(load, 80_000, seed=0)
    assert float(err.mean()) == pytest.approx(
        float(ro.client_error.mean()), abs=0.01
    )


def test_graceful_kill_completes_inflight_requests():
    # drain=True (default): killed replicas finish their in-flight
    # work; with capacity to spare no client ever sees an error
    graph = ServiceGraph.from_yaml(LONG_REQUEST)
    chaos = (ChaosEvent(service="worker", start_s=10.0, end_s=30.0,
                        replicas_down=2, drain=True),)
    load = LoadModel(kind="open", qps=50.0)
    engine = Simulator(compile_graph(graph), SimParams(), chaos)
    res = engine.run(load, 2_000, KEY)
    assert not np.asarray(res.client_error).any()
    oracle = OracleSimulator(graph, SimParams(), chaos)
    ro = oracle.run(load, 2_000, seed=0)
    assert not ro.client_error.any()


def test_ungraceful_kill_resets_inflight_requests():
    # drain=False: requests resident on the 2 killed replicas (of 4)
    # at t=10 die with a connection reset.  With 2 s of sleep per
    # request, arrivals in ~[8, 10) are in flight at the kill — about
    # half of them (2/4 replicas) must fail, in engine AND oracle.
    graph = ServiceGraph.from_yaml(LONG_REQUEST)
    chaos = (ChaosEvent(service="worker", start_s=10.0, end_s=30.0,
                        replicas_down=2, drain=False),)
    load = LoadModel(kind="open", qps=50.0)
    engine = Simulator(compile_graph(graph), SimParams(), chaos)
    res = engine.run(load, 2_000, KEY)
    st = np.asarray(res.client_start)
    err = np.asarray(res.client_error)
    lat = np.asarray(res.client_latency)

    oracle = OracleSimulator(graph, SimParams(), chaos)
    ro = oracle.run(load, 2_000, seed=0)

    window = (st >= 7.9) & (st < 10.0)
    window_o = (ro.client_start >= 7.9) & (ro.client_start < 10.0)
    frac_e = float(err[window].mean())
    frac_o = float(ro.client_error[window_o].mean())
    # ~half the straddling requests die (binomial noise over ~100 reqs)
    assert frac_e == pytest.approx(0.5, abs=0.15)
    assert frac_o == pytest.approx(0.5, abs=0.15)
    # requests outside the straddle window are untouched
    assert not err[(st < 7.5) | (st > 10.5)].any()
    assert not ro.client_error[
        (ro.client_start < 7.5) | (ro.client_start > 10.5)
    ].any()
    # a reset client observes the kill instant, not the full sleep
    died = err & window
    if died.any():
        np.testing.assert_array_less(lat[died], 2.0)
        ends = st[died] + lat[died]
        np.testing.assert_allclose(ends, 10.0, atol=0.05)


def test_chaos_toml_bounce_and_drain(tmp_path):
    from isotope_tpu.runner.config import load_toml

    topo = tmp_path / "t.yaml"
    topo.write_text(SIMPLE)
    cfg = tmp_path / "c.toml"
    cfg.write_text(
        f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [100]
num_concurrent_connections = [4]
duration = "60s"

[[chaos]]
service = "entry"
start = "5s"
end = "7s"
period = "10s"
repeat = 3

[[chaos]]
service = "entry"
start = "55s"
end = "58s"
replicas_down = 1
drain = false
"""
    )
    c = load_toml(cfg)
    assert len(c.chaos) == 4
    assert [(e.start_s, e.end_s) for e in c.chaos[:3]] == [
        (5.0, 7.0), (15.0, 17.0), (25.0, 27.0)
    ]
    assert c.chaos[3].drain is False
    assert c.chaos[3].replicas_down == 1
