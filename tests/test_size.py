"""ByteSize decode/format tests.

Coverage mirrors the reference's size/byte_size_test.go (go-units RAMInBytes
semantics: binary 1024-based, case-insensitive suffixes).
"""
import pytest

from isotope_tpu.models.size import (
    ByteSize,
    InvalidSizeStringError,
    NegativeSizeError,
)


@pytest.mark.parametrize(
    "s,expected",
    [
        ("32", 32),
        ("32b", 32),
        ("32B", 32),
        ("32k", 32 * 1024),
        ("32K", 32 * 1024),
        ("32kb", 32 * 1024),
        ("32Kb", 32 * 1024),
        ("32Mb", 32 * 1024 ** 2),
        ("32Gb", 32 * 1024 ** 3),
        ("32Tb", 32 * 1024 ** 4),
        ("32Pb", 32 * 1024 ** 5),
        ("16 KiB", 16 * 1024),
        ("1 KB", 1024),
        ("0.5k", 512),
        ("128", 128),
    ],
)
def test_from_string(s, expected):
    assert ByteSize.from_string(s) == expected


@pytest.mark.parametrize("s", ["", "hello", "-32", "32.3.4k", "32 q"])
def test_from_string_invalid(s):
    with pytest.raises(InvalidSizeStringError):
        ByteSize.from_string(s)


def test_from_int():
    assert ByteSize.from_int(100) == 100
    with pytest.raises(NegativeSizeError):
        ByteSize.from_int(-1)


def test_decode():
    assert ByteSize.decode(1024) == 1024
    assert ByteSize.decode("1k") == 1024


@pytest.mark.parametrize(
    "n,s",
    [
        (0, "0B"),
        (128, "128B"),
        (1024, "1KiB"),
        (1536, "1.5KiB"),
        (1024 ** 2, "1MiB"),
    ],
)
def test_str(n, s):
    # go-units BytesSize: %.4g with binary abbreviations.
    assert str(ByteSize(n)) == s


def test_encode_lossy_sizes_fall_back_to_integer():
    # 123456 formats as "120.6KiB" which re-decodes to 123494 — encode must
    # emit the exact integer instead so round-trips never perturb sizes.
    assert ByteSize(123456).encode() == 123456
    assert ByteSize.decode(ByteSize(123456).encode()) == 123456
    # round sizes keep the pretty form
    assert ByteSize(1024).encode() == "1KiB"
