"""Gradient audit (`vet --grad`): taint classification fixtures.

Unit-level: the taint propagation must kill liveness at the known
killers (floor family, comparisons/integer casts via dtype,
predicate-only select routes) and survive the smooth paths, including
through scan/while carries and pjit/custom-vjp sub-jaxprs.  End to
end: the canonical example's knob classification is pinned
(tests/data/grad_audit_canonical.json), the pass is trace-only, and
the seeded `graddead` injection must surface VET-G001.
"""
import json
import pathlib

import jax
import jax.numpy as jnp
import pytest

from isotope_tpu import cli, telemetry
from isotope_tpu.analysis import grad_audit, jaxpr_audit
from isotope_tpu.analysis.vet import vet_topology_path
from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import DESIGN_PARAMS, LoadModel
from isotope_tpu.sim.engine import Simulator

ROOT = pathlib.Path(__file__).parent.parent
OPEN = LoadModel(kind="open", qps=100.0)

CHAIN = {
    "services": [
        {"name": "a", "isEntrypoint": True, "script": [{"call": "b"}]},
        {"name": "b"},
    ]
}


def _chain_sim():
    return Simulator(compile_graph(ServiceGraph.decode(CHAIN)))


def _write_topo(tmp_path, doc, name="topo.yaml"):
    import yaml

    p = tmp_path / name
    p.write_text(yaml.safe_dump(doc))
    return str(p)


def _taint(fn, seed_idx, *avals):
    """Seed one knob at invar ``seed_idx`` of ``fn``'s jaxpr and run
    the forward taint; returns (out_taints, state)."""
    closed = jax.make_jaxpr(fn)(*avals)
    state = grad_audit._TaintState()
    in_t = [{} for _ in closed.jaxpr.invars]
    in_t[seed_idx]["k"] = (True, None)
    outs = grad_audit._analyze(closed.jaxpr, in_t, "", state)
    return outs, state


F32 = jax.ShapeDtypeStruct((), jnp.float32)
V32 = jax.ShapeDtypeStruct((8,), jnp.float32)


# -- taint propagation units ------------------------------------------------


def test_smooth_path_stays_live():
    outs, _ = _taint(lambda x: jnp.exp(x) * 2.0 + 1.0, 0, F32)
    assert outs[0]["k"] == (True, None)


def test_floor_kills_with_named_site():
    outs, state = _taint(lambda x: jnp.floor(x) * 2.0, 0, F32)
    live, killer = outs[0]["k"]
    assert not live and killer == "floor"
    assert list(state.kills["k"]) == ["floor"]


def test_comparison_dtype_kill_names_the_comparison():
    outs, _ = _taint(
        lambda x: (x < 0.5).astype(jnp.float32), 0, F32,
    )
    live, killer = outs[0]["k"]
    assert not live and killer == "lt"


def test_predicate_only_select_names_the_feeder():
    # knob reaches the select ONLY through the predicate: routing,
    # dead, named select_n<-lt
    outs, state = _taint(
        lambda x, y: jnp.where(x < 0.5, y, 2.0), 0, F32, F32,
    )
    # jnp.where traces under a `_where` pjit, hence the path prefix
    live, killer = outs[0]["k"]
    assert not live and killer == "_where/select_n←lt"
    assert "lt" in state.kills["k"]  # first kill = the comparison

    # the same select seeded at a BRANCH stays live (smooth path)
    outs, _ = _taint(
        lambda x, y: jnp.where(x < 0.5, y, 2.0), 1, F32, F32,
    )
    assert outs[0]["k"] == (True, None)


def test_integer_cast_kills():
    outs, _ = _taint(
        lambda x: x.astype(jnp.int32).astype(jnp.float32), 0, F32,
    )
    live, killer = outs[0]["k"]
    assert not live and killer == "convert_element_type"


def test_scan_carry_fixpoint_propagates_liveness():
    def f(x):
        def body(c, _):
            return c * 0.5 + x, c
        return jax.lax.scan(body, x, jnp.arange(4.0))

    outs, _ = _taint(f, 0, F32)
    assert outs[0]["k"][0]          # final carry live
    assert outs[1]["k"][0]          # stacked ys live


def test_scan_body_killer_carries_the_path():
    def f(x):
        def body(c, _):
            return jnp.floor(c), None
        return jax.lax.scan(body, x, jnp.arange(4.0))[0]

    outs, state = _taint(f, 0, F32)
    live, killer = outs[0]["k"]
    assert not live and killer == "scan/body/floor"
    assert "scan/body/floor" in state.kills["k"]


def test_while_loop_carry_stays_live():
    def f(x):
        def cond(c):
            return c[1] < 3
        def body(c):
            return (c[0] * 2.0, c[1] + 1)
        return jax.lax.while_loop(cond, body, (x, 0))[0]

    outs, _ = _taint(f, 0, F32)
    assert outs[0]["k"][0]


def test_pjit_body_is_descended():
    inner = jax.jit(lambda x: jnp.floor(x) * 3.0)
    outs, _ = _taint(lambda x: inner(x) + 1.0, 0, F32)
    live, killer = outs[0]["k"]
    assert not live and killer.endswith("/floor")


def test_custom_vjp_body_is_descended():
    @jax.custom_vjp
    def f(x):
        return x * 2.0

    f.defvjp(lambda x: (f(x), x), lambda r, g: (g * 2.0,))
    outs, _ = _taint(lambda x: f(x) + 1.0, 0, F32)
    assert outs[0]["k"][0]          # smooth custom-vjp body: live


def test_float_scatter_add_records_g003_site():
    def f(x):
        return jnp.zeros((4,), jnp.float32).at[0].add(x)

    outs, state = _taint(f, 0, F32)
    assert outs[0]["k"][0]
    assert any("scatter" in s for s in state.scatter["k"])


def test_iter_eqns_descends_pjit_and_custom_vjp():
    """Satellite pin: the shared walker (jaxpr_audit.iter_eqns)
    surfaces defects wrapped under pjit and custom_vjp bodies."""
    @jax.custom_vjp
    def noisy(x):
        jax.debug.callback(lambda v: None, x)
        return x * 2.0

    noisy.defvjp(lambda x: (noisy(x), x), lambda r, g: (g * 2.0,))

    closed = jax.make_jaxpr(
        lambda x: jax.jit(noisy)(x) + 1.0
    )(V32)
    rules = {f.rule for f in jaxpr_audit.audit_jaxpr(closed)}
    assert "VET-J001" in rules
    prims = {str(e.primitive) for e, _ in jaxpr_audit.iter_eqns(closed)}
    assert "mul" in prims           # reached the innermost body


# -- registry & engine classification ---------------------------------------


def test_design_params_registry_is_well_formed():
    names = [p.name for p in DESIGN_PARAMS]
    assert len(names) == len(set(names))
    for p in DESIGN_PARAMS:
        for invar in p.invars:
            assert invar in grad_audit.GRAD_INVARS, (p.name, invar)
        if not p.traced:
            assert p.constant_site, p.name


def test_chain_audit_classifies_every_knob(monkeypatch):
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    finds, doc = grad_audit.audit_grad(_chain_sim(), OPEN)
    assert doc["schema"] == grad_audit.SCHEMA
    assert set(doc["classes"]) == {p.name for p in DESIGN_PARAMS}
    assert doc["classes"]["qps_scale"] == grad_audit.CLASS_DIFFERENTIABLE
    assert doc["classes"]["cpu_time_s"] == grad_audit.CLASS_DIFFERENTIABLE
    assert doc["classes"]["timeout_ladder"] == grad_audit.CLASS_CONSTANT
    # zero error rates elide the 5xx coin: the knob is inert -> dead
    assert doc["classes"]["error_rate_scale"] == grad_audit.CLASS_DEAD
    assert doc["eqns_walked"] > 0
    rules = {f.rule for f in finds}
    assert "VET-G001" in rules and "VET-G002" in rules
    # quantile/error-count objectives carry no live taint (VET-G004)
    assert "latency_hist" in doc["vacuous_objectives"]
    (g4,) = [f for f in finds if f.rule == "VET-G004"]
    assert "latency_hist" in g4.message


def test_canonical_classification_is_pinned(monkeypatch):
    """Tier-1 pin: a refactor that silently kills a
    previously-differentiable knob (or promotes a trace constant)
    must fail loudly against tests/data/grad_audit_canonical.json."""
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    expected = json.loads(
        (ROOT / "tests/data/grad_audit_canonical.json").read_text()
    )
    g = ServiceGraph.from_yaml_file(
        str(ROOT / expected["topology"])
    )
    _, doc = grad_audit.audit_grad(Simulator(compile_graph(g)), OPEN)
    assert doc["classes"] == expected["classes"]
    assert doc["vacuous_objectives"] == expected["vacuous_objectives"]


def test_errors_example_names_killing_primitive(monkeypatch):
    """The shipped canonical-errors example demonstrates the
    gradient-dead class with a NAMED killer: the 5xx coin's
    comparison, on the scan body path."""
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    g = ServiceGraph.from_yaml_file(
        str(ROOT / "examples/topologies/canonical-errors.yaml")
    )
    finds, doc = grad_audit.audit_grad(Simulator(compile_graph(g)), OPEN)
    (k,) = [k for k in doc["knobs"] if k["name"] == "error_rate_scale"]
    assert k["class"] == grad_audit.CLASS_DEAD
    assert k["kills"] and k["kills"][0] == "scan/body/lt"
    (f,) = [f for f in finds if f.rule == "VET-G001"]
    assert "scan/body/lt" in f.message and f.path == "scan/body/lt"


def test_grad_audit_is_trace_only(monkeypatch, tmp_path):
    """Pinned: `vet --grad` performs NO device execution — no jit
    first-call, no backend compile, engine entry points never run."""
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)

    def boom(*a, **k):  # pragma: no cover - must never run
        raise AssertionError("grad audit executed the engine")

    monkeypatch.setattr(Simulator, "run", boom)
    monkeypatch.setattr(Simulator, "run_summary", boom)
    telemetry.reset()
    path = _write_topo(tmp_path, CHAIN)
    report = vet_topology_path(path, load=OPEN, grad=True)
    assert "grad" in report.meta
    assert telemetry.counter_get("jit_first_calls") == 0.0
    assert telemetry.phase_seconds("compile.backend") == 0.0
    # per-rule telemetry counters folded in (vet._count)
    assert telemetry.counter_get("vet_rule.VET-G002") > 0


def test_graddead_injection_surfaces_g001(monkeypatch):
    monkeypatch.setenv("ISOTOPE_VET_INJECT", "graddead")
    finds, doc = grad_audit.audit_grad(_chain_sim(), OPEN)
    assert doc["classes"]["cpu_time_s"] == grad_audit.CLASS_DEAD
    (f,) = [
        f for f in finds
        if f.rule == "VET-G001" and "cpu_time_s" in f.message
    ]
    assert "floor" in f.message and f.path == "floor"


def test_unknown_inject_kind_still_raises(monkeypatch):
    monkeypatch.setenv("ISOTOPE_VET_INJECT", "gradded")
    with pytest.raises(ValueError, match="unknown"):
        jaxpr_audit.inject_spec()


def test_cli_grad_json_artifact(tmp_path, monkeypatch):
    monkeypatch.delenv("ISOTOPE_VET_INJECT", raising=False)
    topo = _write_topo(tmp_path, CHAIN)
    out = tmp_path / "grad.json"
    # --grad-json implies --grad; VET-G findings are warn/info: exit 0
    assert cli.main(["vet", "--grad-json", str(out), topo]) == 0
    doc = json.loads(out.read_text())
    assert doc["schema"] == "isotope-gradaudit/v1"
    (audit,) = doc["audits"]
    assert audit["topology"] == topo
    assert set(audit["classes"]) == {p.name for p in DESIGN_PARAMS}
    assert audit["objectives"]["latency_sum"]  # live knobs recorded
