"""Retry / timeout extension tests.

These knobs extend the reference's call grammar (which defers both to
Istio VirtualService policy): an attempt fails on a 5xx response, a
connection failure (down service), or a timeout; failed attempts retry up
to ``retries`` times; an exhausted call whose last attempt was a
transport-class failure fails the caller (like handler.go:66-76), while an
exhausted 5xx does not (executable.go:132-143).
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.models.script import InvalidCommandError, RequestCommand
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import ChaosEvent

KEY = jax.random.PRNGKey(9)
DET = SimParams(service_time="deterministic")
CPU = DET.cpu_time_s
RTT1 = 2 * DET.network.base_latency_s
QUIET = LoadModel(kind="open", qps=10.0)


def run(yaml, n=4000, chaos=(), load=QUIET):
    compiled = compile_graph(ServiceGraph.from_yaml(yaml))
    return compiled, Simulator(compiled, DET, chaos).run(load, n, KEY)


# -- IR ---------------------------------------------------------------------

def test_decode_encode_roundtrip():
    cmd = RequestCommand.decode(
        {"service": "b", "timeout": "250ms", "retries": 2},
        RequestCommand(service_name=""),
    )
    assert cmd.timeout == pytest.approx(0.25)
    assert cmd.retries == 2
    enc = cmd.encode()["call"]
    assert enc["timeout"] == "250ms" and enc["retries"] == 2
    again = RequestCommand.decode(enc, RequestCommand(service_name=""))
    assert again == cmd


def test_decode_validation():
    default = RequestCommand(service_name="")
    with pytest.raises(InvalidCommandError):
        RequestCommand.decode({"service": "b", "timeout": 5}, default)
    with pytest.raises(InvalidCommandError):
        RequestCommand.decode({"service": "b", "timeout": "-1s"}, default)
    with pytest.raises(InvalidCommandError):
        RequestCommand.decode({"service": "b", "retries": -1}, default)
    with pytest.raises(InvalidCommandError):
        RequestCommand.decode({"service": "b", "retries": True}, default)


# -- compiler ---------------------------------------------------------------

def test_attempts_unrolled_as_sibling_hops():
    c = compile_graph(
        ServiceGraph.from_yaml(
            """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: flaky, retries: 2}
- name: flaky
  errorRate: 50%
"""
        )
    )
    assert c.num_hops == 4  # entry + 3 attempts
    root = c.levels[0]
    assert root.num_calls == 1
    assert root.att_child.shape == (3, 1)
    assert root.att_valid.all()
    # static reach discounts attempts by the target's error rate
    np.testing.assert_allclose(c.hop_reach, [1.0, 1.0, 0.5, 0.25])
    visits = c.expected_visits()
    assert visits[c.services.index_of("flaky")] == pytest.approx(1.75)


# -- engine -----------------------------------------------------------------

def test_timeout_caps_call_and_fails_caller():
    _, res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: slow, timeout: 20ms}
  - sleep: 500ms
- name: slow
  script:
  - sleep: 100ms
"""
    )
    # every call times out: entry 500s, trailing sleep skipped, the slow
    # callee itself still ran (and is a hop event)
    assert np.asarray(res.client_error).all()
    assert np.asarray(res.hop_sent[:, 1]).all()
    want = RTT1 + CPU + 0.020
    assert np.median(res.client_latency) == pytest.approx(want, rel=1e-3)


def test_retries_recover_from_downstream_500s():
    compiled, res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: flaky, retries: 2}
- name: flaky
  errorRate: 50%
""",
        n=20000,
    )
    # 500s never propagate: client clean either way
    assert not np.asarray(res.client_error).any()
    sent = np.asarray(res.hop_sent)
    # attempt chain: 1 + 0.5 + 0.25 expected executions per request
    attempts_per_req = sent[:, 1:].sum(1)
    assert attempts_per_req.mean() == pytest.approx(1.75, rel=0.03)
    # ~87.5% of requests end with a 200 from flaky on some attempt
    err = np.asarray(res.hop_error)
    last_ok = (sent[:, 1:] & ~err[:, 1:]).any(axis=1)
    assert last_ok.mean() == pytest.approx(1 - 0.5**3, abs=0.02)


def test_retries_against_down_service_fail_transport():
    _, res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: dead, retries: 3}
- name: dead
""",
        chaos=[ChaosEvent("dead", 0.0, 1e6)],
    )
    assert np.asarray(res.client_error).all()
    # connection-refused attempts never execute on the dead service
    assert int(np.asarray(res.hop_sent)[:, 1:].sum()) == 0
    # and they cost ~nothing
    want = RTT1 + CPU
    assert np.median(res.client_latency) == pytest.approx(want, rel=1e-3)


def test_retry_after_timeout_adds_serial_attempt_durations():
    _, res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: slow, timeout: 10ms, retries: 1}
- name: slow
  script:
  - sleep: 30ms
"""
    )
    # both attempts time out at 10ms each, serially
    assert np.asarray(res.client_error).all()
    want = RTT1 + CPU + 0.010 + 0.010
    assert np.median(res.client_latency) == pytest.approx(want, rel=1e-3)
    # both attempts executed on the slow service
    assert np.asarray(res.hop_sent)[:, 1:].all()


def test_generous_timeout_is_a_noop():
    _, res = run(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - call: {service: leaf, timeout: 10s, retries: 2}
- name: leaf
"""
    )
    assert not np.asarray(res.client_error).any()
    sent = np.asarray(res.hop_sent)
    assert sent[:, 1].all() and not sent[:, 2:].any()  # no retries needed
    want = RTT1 + CPU + (RTT1 + CPU)
    assert np.median(res.client_latency) == pytest.approx(want, rel=1e-3)
