"""Go-duration parse/format tests (time.ParseDuration grammar)."""
import pytest

from isotope_tpu.utils.duration import (
    InvalidDurationError,
    format_duration_ns,
    parse_duration_ns,
    parse_duration_seconds,
)


@pytest.mark.parametrize(
    "s,ns",
    [
        ("0", 0),
        ("100ms", 100_000_000),
        ("1s", 1_000_000_000),
        ("1.5s", 1_500_000_000),
        ("10ns", 10),
        ("5us", 5_000),
        ("5µs", 5_000),
        ("2m", 120_000_000_000),
        ("1h", 3_600_000_000_000),
        ("1h2m3s", 3_723_000_000_000),
        ("-5s", -5_000_000_000),
        ("1m30s", 90_000_000_000),
    ],
)
def test_parse(s, ns):
    assert parse_duration_ns(s) == ns


@pytest.mark.parametrize("s", ["", "5", "abc", "1x", "s", "5 s"])
def test_parse_invalid(s):
    with pytest.raises(InvalidDurationError):
        parse_duration_ns(s)


@pytest.mark.parametrize(
    "ns,s",
    [
        (0, "0s"),
        (10, "10ns"),
        (5_000, "5µs"),
        (100_000_000, "100ms"),
        (1_500_000_000, "1.5s"),
        (90_000_000_000, "1m30s"),
        (3_723_000_000_000, "1h2m3s"),
    ],
)
def test_format(ns, s):
    assert format_duration_ns(ns) == s


def test_seconds_roundtrip():
    assert parse_duration_seconds("250ms") == pytest.approx(0.25)
