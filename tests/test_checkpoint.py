"""Sweep checkpoint/resume: a killed sweep resumes to an identical
benchmark.csv (SURVEY.md §5.4; the reference's durability is a
persistent-disk Prometheus + off-pod Fortio JSONs)."""
import json
import pathlib

import pytest

from isotope_tpu import cli
from isotope_tpu.runner import load_toml, run_experiment

TOPO = pathlib.Path(__file__).parent.parent / "examples/topologies/canonical.yaml"


def config(tmp_path):
    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE", "ISTIO"]

[client]
qps = [200, 400]
num_concurrent_connections = [8]
duration = "60s"
load_kind = "open"

[sim]
num_requests = 3000
seed = 11
"""
    )
    return load_toml(cfg)


class Kill(Exception):
    pass


def test_kill_and_resume_identical_csv(tmp_path):
    cfg = config(tmp_path)

    # ground truth: one uninterrupted sweep
    full_dir = tmp_path / "full"
    run_experiment(cfg, out_dir=str(full_dir))
    want_csv = (full_dir / "benchmark.csv").read_text()

    # killed after 2 of 4 runs
    resumed_dir = tmp_path / "resumed"
    count = 0

    def killer(label):
        nonlocal count
        count += 1
        if count > 2:
            raise Kill(label)

    with pytest.raises(Kill):
        run_experiment(cfg, out_dir=str(resumed_dir), progress=killer)
    ckpt = (resumed_dir / "checkpoint.jsonl").read_text().splitlines()
    assert len(ckpt) == 1 + 2  # header + the 2 completed runs

    # resume: only the remaining 2 runs execute
    ran = []
    results = run_experiment(
        cfg, out_dir=str(resumed_dir), progress=ran.append
    )
    assert len(ran) == 2
    assert len(results) == 4
    got_csv = (resumed_dir / "benchmark.csv").read_text()
    # identical rows except the wall-clock StartTime column
    for want, got in zip(want_csv.splitlines(), got_csv.splitlines()):
        w = want.split(",")
        g = got.split(",")
        del w[1], g[1]  # StartTime
        assert w == g


def test_config_change_invalidates_checkpoint(tmp_path):
    cfg = config(tmp_path)
    out = tmp_path / "out"
    run_experiment(cfg, out_dir=str(out))

    cfg2 = config(tmp_path)
    cfg2 = cfg2.__class__(**{**cfg2.__dict__, "seed": 12})
    ran = []
    run_experiment(cfg2, out_dir=str(out), progress=ran.append)
    assert len(ran) == 4  # everything reruns


def test_topology_edit_invalidates_checkpoint(tmp_path):
    # same config object, but the YAML the paths point at changed:
    # resuming stale results would silently simulate the old graph
    topo = tmp_path / "t.yaml"
    topo.write_text(TOPO.read_text())
    cfg = config(tmp_path)
    cfg = cfg.__class__(**{**cfg.__dict__,
                           "topology_paths": (str(topo),)})
    out = tmp_path / "out"
    run_experiment(cfg, out_dir=str(out))

    topo.write_text(TOPO.read_text() + "- name: extra\n")
    ran = []
    run_experiment(cfg, out_dir=str(out), progress=ran.append)
    assert len(ran) == 4  # checkpoint invalidated, everything reruns


def test_completed_sweep_replays_for_free(tmp_path):
    cfg = config(tmp_path)
    out = tmp_path / "out"
    run_experiment(cfg, out_dir=str(out))
    ran = []
    results = run_experiment(cfg, out_dir=str(out), progress=ran.append)
    assert ran == []
    assert len(results) == 4
    # restored results carry their persisted prometheus text
    assert all(r.prometheus_text for r in results)


def test_cli_fresh_flag_reruns(tmp_path, capsys):
    cfg_path = tmp_path / "exp.toml"
    cfg_path.write_text(
        f"""
topology_paths = ["{TOPO}"]
environments = ["NONE"]

[client]
qps = [100]
num_concurrent_connections = [4]
duration = "30s"
load_kind = "open"

[sim]
num_requests = 1000
"""
    )
    out = tmp_path / "o"
    assert cli.main(["sweep", str(cfg_path), "-o", str(out)]) == 0
    capsys.readouterr()
    # resume: nothing runs
    assert cli.main(["sweep", str(cfg_path), "-o", str(out)]) == 0
    assert "running" not in capsys.readouterr().err
    # fresh: run again
    assert cli.main(
        ["sweep", str(cfg_path), "-o", str(out), "--fresh"]
    ) == 0
    assert "running" in capsys.readouterr().err


def test_truncated_tail_record_is_tolerated(tmp_path):
    # a SIGKILL mid-append leaves a partial final line; resume must
    # treat it as the lost in-flight run, not crash
    cfg = config(tmp_path)
    out = tmp_path / "out"
    run_experiment(cfg, out_dir=str(out))
    ckpt = out / "checkpoint.jsonl"
    lines = ckpt.read_text().splitlines()
    ckpt.write_text(
        "\n".join(lines[:-1]) + "\n" + lines[-1][: len(lines[-1]) // 2]
    )
    ran = []
    results = run_experiment(cfg, out_dir=str(out), progress=ran.append)
    assert len(ran) == 1  # only the truncated run re-executes
    assert len(results) == 4


def test_corrupted_middle_record_quarantined(tmp_path):
    # bit rot / torn write in the MIDDLE of the checkpoint: only that
    # record's run re-executes; completed records after it stay trusted
    # (records are self-contained and label-keyed, not positional)
    cfg = config(tmp_path)
    out = tmp_path / "out"
    run_experiment(cfg, out_dir=str(out))
    ckpt = out / "checkpoint.jsonl"
    lines = ckpt.read_text().splitlines()
    assert len(lines) == 5  # header + 4 records
    lines[2] = lines[2][: len(lines[2]) // 2]  # corrupt record #2
    ckpt.write_text("\n".join(lines) + "\n")
    ran = []
    results = run_experiment(cfg, out_dir=str(out), progress=ran.append)
    assert len(ran) == 1  # only the quarantined record's run
    assert len(results) == 4
    assert not any(r.failed for r in results)


def test_failed_case_recorded_and_sweep_continues(tmp_path):
    # an unrecoverable OOM (degradation disabled) fails ONE case; the
    # sweep records it and completes the remaining three
    from isotope_tpu.resilience import ResiliencePolicy, faults

    cfg = config(tmp_path)
    out = tmp_path / "out"
    strict = ResiliencePolicy(max_retries=0, degrade=False,
                              sleep=lambda s: None)
    # the test env's 8-device virtual mesh routes runs through the
    # sharded path; its compute phase is the injection site
    faults.install("oom:sharded.compute:1")
    try:
        results = run_experiment(cfg, out_dir=str(out), policy=strict)
    finally:
        faults.clear()
    assert [r.failed for r in results] == [True, False, False, False]
    recs = [
        json.loads(ln)
        for ln in (out / "checkpoint.jsonl").read_text().splitlines()[1:]
    ]
    assert recs[0]["failed"] and "RESOURCE_EXHAUSTED" in recs[0]["error"]
    assert len(recs) == 4
    # the failed case's row is absent from the CSV (3 data rows)
    csv = (out / "benchmark.csv").read_text().splitlines()
    assert len(csv) == 1 + 3

    # resume: the failed case retries, completed cases don't re-run —
    # and the final CSV matches an uninterrupted sweep's exactly
    full_dir = tmp_path / "full"
    run_experiment(cfg, out_dir=str(full_dir))
    ran = []
    results = run_experiment(cfg, out_dir=str(out), progress=ran.append)
    assert len(ran) == 1
    assert not any(r.failed for r in results)
    want = (full_dir / "benchmark.csv").read_text().splitlines()
    got = (out / "benchmark.csv").read_text().splitlines()
    for w_line, g_line in zip(want, got):
        w, g = w_line.split(","), g_line.split(",")
        del w[1], g[1]  # StartTime
        assert w == g


def test_checkpoint_records_are_wellformed(tmp_path):
    cfg = config(tmp_path)
    out = tmp_path / "out"
    run_experiment(cfg, out_dir=str(out))
    lines = (out / "checkpoint.jsonl").read_text().splitlines()
    header = json.loads(lines[0])
    assert "config" in header
    for line in lines[1:]:
        rec = json.loads(line)
        assert {"label", "topology", "environment", "flat", "window",
                "fortio_json"} <= set(rec)
        assert (out / f"{rec['label']}.prom").exists()
        assert (out / f"{rec['label']}.json").exists()
