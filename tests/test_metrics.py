"""Metrics layer tests: Prometheus series parity + Fortio schema."""
import json

import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics import (
    DURATION_BUCKETS,
    MetricsCollector,
    SIZE_BUCKETS,
    convert_data,
    fortio_result,
    trim_window_summary,
    write_csv,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator

YAML = """
defaults:
  requestSize: 128
  responseSize: 512
services:
- name: entry
  isEntrypoint: true
  script:
  - call: mid
- name: mid
  errorRate: 50%
  script:
  - call: leaf
- name: leaf
"""


@pytest.fixture(scope="module")
def run():
    compiled = compile_graph(ServiceGraph.from_yaml(YAML))
    sim = Simulator(compiled, SimParams(service_time="deterministic"))
    res = sim.run(LoadModel(kind="open", qps=10.0), 2000, jax.random.PRNGKey(3))
    return compiled, res


def test_bucket_layouts_match_reference():
    # srv/prometheus/handler.go:27-35
    assert len(DURATION_BUCKETS) == 32
    assert DURATION_BUCKETS[0] == 0.007 and DURATION_BUCKETS[-1] == 0.5
    np.testing.assert_allclose(SIZE_BUCKETS, [10.0 ** e for e in range(10)])


def test_counters_respect_error_gating(run):
    compiled, res = run
    m = MetricsCollector(compiled).collect(res)
    inc = np.asarray(m.incoming_total)
    i = {n: inc[k] for k, n in enumerate(compiled.services.names)}
    # entry sees all 2000; mid sees all (entry has no errorRate);
    # leaf sees only requests where mid did NOT error (~50%)
    assert i["entry"] == 2000
    assert i["mid"] == 2000
    assert 850 < i["leaf"] < 1150
    # duration histogram count: 200-code mid ~= leaf count, 500-code the rest
    dur = np.asarray(m.duration_hist)
    mid = compiled.services.index_of("mid")
    assert dur[mid, 0].sum() == i["leaf"]
    assert dur[mid, 1].sum() == 2000 - i["leaf"]


def test_edges_and_outgoing(run):
    compiled, res = run
    coll = MetricsCollector(compiled)
    m = coll.collect(res)
    names = compiled.services.names
    labeled = {
        (
            "client" if s < 0 else names[s],
            names[d],
        ): float(np.asarray(m.outgoing_total)[e])
        for e, (s, d) in enumerate(coll.edges)
    }
    assert labeled[("client", "entry")] == 2000
    assert labeled[("entry", "mid")] == 2000
    assert labeled[("mid", "leaf")] == float(
        np.asarray(m.incoming_total)[compiled.services.index_of("leaf")]
    )


def test_prometheus_text_parses(run):
    compiled, res = run
    coll = MetricsCollector(compiled)
    text = coll.to_text(coll.collect(res))
    # all five reference series present, with reference names
    for series in (
        "service_incoming_requests_total",
        "service_outgoing_requests_total",
        "service_outgoing_request_size",
        "service_request_duration_seconds",
        "service_response_size",
    ):
        assert f"# TYPE {series}" in text
    # bucket monotonicity + +Inf == count for one histogram
    lines = [
        line
        for line in text.splitlines()
        if line.startswith(
            'service_request_duration_seconds_bucket{service="entry",code="200"'
        )
    ]
    vals = [float(line.rsplit(" ", 1)[1]) for line in lines]
    assert vals == sorted(vals)
    count = [
        line
        for line in text.splitlines()
        if line.startswith(
            'service_request_duration_seconds_count{service="entry",code="200"'
        )
    ]
    assert float(count[0].rsplit(" ", 1)[1]) == vals[-1]


def test_fortio_result_roundtrips_through_reference_flattener(run):
    _, res = run
    load = LoadModel(kind="open", qps=10.0)
    doc = fortio_result(res, load, labels="canonical_none", response_size_bytes=512)
    json.dumps(doc)  # must be JSON-serializable
    flat = convert_data(doc)
    assert flat["Labels"] == "canonical_none"
    assert flat["RequestedQPS"] == 10
    assert flat["NumThreads"] == 64
    assert flat["p50"] > 0 and flat["p999"] >= flat["p99"] >= flat["p50"]
    assert flat["errorPercent"] == 0.0  # downstream errors don't hit client
    assert flat["Payload"] == 512
    h = doc["DurationHistogram"]
    assert h["Count"] == 2000
    assert sum(d["Count"] for d in h["Data"]) == 2000


def test_requested_qps_max_flattens_to_sentinel(run):
    _, res = run
    doc = fortio_result(res, LoadModel(kind="closed", qps=None, connections=8))
    assert convert_data(doc)["RequestedQPS"] == 99999999


def test_trim_window_semantics(run):
    compiled, res = run
    # 2000 req at 10qps => ~200s run; window = [62, 62+min(200-92,180))
    s = trim_window_summary(
        res,
        LoadModel(kind="open", qps=10.0),
        service_names=compiled.services.names,
        replicas=compiled.services.replicas,
    )
    assert not s.discarded
    assert s.start_s == 62
    assert 90 < s.duration_s <= 180
    assert s.qps == pytest.approx(10.0, rel=0.15)
    assert set(s.percentiles_us) == {"p50", "p75", "p90", "p99", "p999"}
    assert all(v >= 0 for v in s.cpu_cores.values())


def test_short_run_discarded():
    compiled = compile_graph(
        ServiceGraph.from_yaml("services:\n- name: a\n  isEntrypoint: true\n")
    )
    sim = Simulator(compiled)
    res = sim.run(LoadModel(kind="open", qps=100.0, duration_s=10), 1000,
                  jax.random.PRNGKey(0))
    s = trim_window_summary(res, LoadModel(kind="open", qps=100.0))
    assert s.discarded and "less than minimum" in s.discard_reason


def test_high_error_run_discarded():
    compiled = compile_graph(
        ServiceGraph.from_yaml(
            "services:\n- name: a\n  isEntrypoint: true\n  errorRate: 50%\n"
        )
    )
    res = Simulator(compiled).run(
        LoadModel(kind="open", qps=100.0), 20000, jax.random.PRNGKey(0)
    )
    s = trim_window_summary(res, LoadModel(kind="open", qps=100.0))
    assert s.discarded and "errors" in s.discard_reason


def test_write_csv(tmp_path, run):
    _, res = run
    doc = fortio_result(res, LoadModel(kind="open", qps=10.0), labels="x")
    flat = convert_data(doc)
    path = tmp_path / "out.csv"
    write_csv("Labels,p50,nothere", [flat], path)
    lines = path.read_text().splitlines()
    assert lines[0] == "Labels,p50,nothere"
    assert lines[1].startswith("x,") and lines[1].endswith(",-")


def test_bucket_index_matches_searchsorted_edges():
    import numpy as np
    import jax.numpy as jnp
    from isotope_tpu.metrics.histogram import (
        EDGES, NUM_BUCKETS, bucket_index,
    )

    rng = np.random.default_rng(0)
    x = np.concatenate([
        [0.0, 1e-9, 9.99e-7, 1e-6, 5e-6, 9.9, 10.0, 11.0, 1e3],
        rng.uniform(1e-6, 10.0, 2000),
        np.exp(rng.uniform(np.log(1e-6), np.log(10.0), 2000)),
    ]).astype(np.float32)
    want = np.searchsorted(EDGES[1:-1], x, side="right")
    got = np.asarray(bucket_index(jnp.asarray(x)))
    # float32 log math may land exactly-on-edge values one bucket off
    assert (np.abs(got - want) <= 1).all()
    assert (got[np.abs(got - want) == 1].size / got.size) < 0.01
    # NaN keeps searchsorted's overflow-bucket behavior
    nan_idx = np.asarray(bucket_index(jnp.asarray([np.nan])))
    assert nan_idx[0] == NUM_BUCKETS - 1
