"""The 5-way sidecar-mode matrix (runner.py:93-99,178-197 parity)."""
import jax
import numpy as np
import pytest
import yaml

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.runner.config import DEFAULT_ENVIRONMENTS, load_toml
from isotope_tpu.runner.run import run_experiment
from isotope_tpu.sim import LoadModel, SimParams, Simulator

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  script: [{call: mid}]
- name: mid
  script: [{call: leaf}]
- name: leaf
"""

MODES = ["baseline", "clientsidecar", "serversidecar", "both", "ingress"]


def mean_latency(mode: str) -> float:
    params = DEFAULT_ENVIRONMENTS[mode].apply(
        SimParams(service_time="deterministic")
    )
    sim = Simulator(
        compile_graph(ServiceGraph.decode(yaml.safe_load(CHAIN))), params
    )
    res = sim.run(
        LoadModel(kind="open", qps=1.0), 64, jax.random.PRNGKey(0)
    )
    return float(np.asarray(res.client_latency).mean())


def test_mode_latency_ordering():
    lat = {m: mean_latency(m) for m in MODES}
    # one-sided sidecars tax every edge equally; both doubles the tax
    assert lat["baseline"] < lat["clientsidecar"]
    assert lat["clientsidecar"] == pytest.approx(lat["serversidecar"])
    assert lat["serversidecar"] < lat["both"]
    # a 3-hop chain quietly: each one-way pass costs 250us per edge;
    # 3 edges (client->entry, entry->mid, mid->leaf), out + back
    per_pass = 2 * 3 * 250e-6
    assert lat["clientsidecar"] - lat["baseline"] == pytest.approx(
        per_pass, rel=0.02
    )
    assert lat["both"] - lat["baseline"] == pytest.approx(
        2 * per_pass, rel=0.02
    )
    # ingress = server sidecars + one gateway traversal on the entry edge
    assert lat["ingress"] - lat["serversidecar"] == pytest.approx(
        2 * 250e-6, rel=0.05
    )


def test_istio_alias_equals_both():
    assert mean_latency("both") == pytest.approx(mean_latency("ISTIO"))


@pytest.mark.slow
def test_sweep_emits_one_row_per_mode(tmp_path):
    topo = tmp_path / "chain.yaml"
    topo.write_text(CHAIN)
    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{topo}"]
environments = ["baseline", "clientsidecar", "serversidecar", "both",
                "ingress"]

[client]
qps = [200]
num_concurrent_connections = [8]
duration = "120s"
load_kind = "open"

[sim]
num_requests = 4000
seed = 1
"""
    )
    results = run_experiment(load_toml(cfg), out_dir=str(tmp_path / "out"))
    assert [r.environment for r in results] == MODES
    rows = (tmp_path / "out" / "benchmark.csv").read_text().splitlines()
    assert len(rows) == 1 + len(MODES)
    p50 = {
        r.environment: r.flat["p50"] for r in results
    }
    assert p50["baseline"] < p50["both"]
    assert p50["serversidecar"] < p50["ingress"]


def test_latency_toml_carries_five_modes():
    import pathlib

    cfg = load_toml(
        pathlib.Path(__file__).parent.parent / "configs/latency.toml"
    )
    assert [e.name for e in cfg.environments] == MODES


def test_env_override_can_tune_proxy_latency(tmp_path):
    topo = tmp_path / "chain.yaml"
    topo.write_text(CHAIN)
    cfg = tmp_path / "exp.toml"
    cfg.write_text(
        f"""
topology_paths = ["{topo}"]
environments = ["both"]

[environment.both]
proxy_latency = "1ms"
"""
    )
    env = load_toml(cfg).environments[0]
    assert env.client_proxy and env.server_proxy
    base = SimParams()
    assert env.apply(base).network.base_latency_s == pytest.approx(
        base.network.base_latency_s + 2e-3
    )
