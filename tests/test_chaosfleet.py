"""Chaos fleets (ISSUE 15): protected Monte Carlo ensembles with
per-member failure schedules and importance-split rare-outage
estimation.

The pins the feature's contract rests on:

- the splitting estimator matches brute-force Monte Carlo on a COMMON
  event (CIs overlap, estimate unbiased within tolerance) and
  resolves a constructed p ~ 1e-4 event with a nonzero estimate at
  <= 10% of the brute-force member budget;
- a protected fleet member k is BIT-IDENTICAL to its solo
  ``run_policies`` (summary + recorder windows + actuation series);
- per-member chaos with the IDENTITY jitter spec is bit-identical to
  the PR 12 fleet (same schedule on every member), and a member
  running an explicit solo schedule matches the solo Simulator with
  that schedule;
- the jittered schedules preserve the solo cut structure (the
  shape-aligned contract the stacked tables rely on);
- the runner dispatches protected cases as fleets (no solo fallback)
  with member 0 bit-equal to the pre-fleet solo protected run, and
  dumps the worst member's stamped postmortem artifacts;
- VET-T024/T025 and the isotope-ensemble/v2 splitting block.
"""
import json

import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph, compile_policies
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.resilience import faults
from isotope_tpu.sim import splitting as split_mod
from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator
from isotope_tpu.sim.ensemble import EnsembleSpec

KEY = jax.random.PRNGKey(7)
OPEN = LoadModel(kind="open", qps=4_000.0)
N, BLOCK, WIN = 2_048, 1_024, 0.25

STORM = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
  errorRate: 0.5%
policies:
  defaults:
    retry_budget: {budget_percent: 25%}
  worker:
    breaker: {max_pending: 6, max_connections: 64,
              consecutive_errors: 5, base_ejection: 2s}
    autoscaler: {min_replicas: 2, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s}
"""

CHAOS = (ChaosEvent("worker", 0.1, 0.3, replicas_down=3),)
JITTER = faults.ChaosJitterSpec(time=0.3, magnitude=0.5, seed=11)


@pytest.fixture(scope="module")
def storm():
    g = ServiceGraph.from_yaml(STORM)
    compiled = compile_graph(g)
    return g, compiled, compile_policies(g, compiled)


@pytest.fixture(scope="module")
def psim(storm):
    _, compiled, pol = storm
    return Simulator(
        compiled, SimParams(timeline=True), chaos=CHAOS, policies=pol
    )


@pytest.fixture(scope="module")
def pfleet(psim):
    """The module's canonical 3-member seeds-only protected fleet."""
    return psim.run_policies_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(3, mode="map"),
        block_size=BLOCK, trim=True, window_s=WIN,
    )


# -- importance splitting (sim/splitting.py) --------------------------------


def _synthetic_eval(components: int):
    """severity = mean of C+1 hashed uniforms — analytically tailed."""
    def ev(cs, ws):
        u = (np.asarray(cs, np.uint64) * 2654435761 % 2**32) / 2**32
        uw = (np.asarray(ws, np.uint64) * 2654435761 % 2**32) / 2**32
        return (u.sum(axis=1) + uw) / (components + 1)

    return ev


def _mean_tail_quantile(components: int, p: float) -> float:
    rng = np.random.default_rng(0)
    big = rng.random((2_000_000, components + 1)).mean(axis=1)
    return float(np.quantile(big, 1.0 - p))


def test_split_common_event_unbiased_and_ci_overlap():
    C = 6
    ev = _synthetic_eval(C)
    t = _mean_tail_quantile(C, 0.3)
    # brute-force reference CI at the same budget class
    rng = np.random.default_rng(1)
    brute = ev(rng.integers(1, 2**31, size=(512, C)),
               rng.integers(1, 2**31, size=512))
    from isotope_tpu.sim.ensemble import wilson_interval

    k = int((brute >= t).sum())
    b_lo, b_hi = wilson_interval(k, len(brute))
    ests = []
    for s in range(20):
        doc = split_mod.subset_estimate(
            ev,
            split_mod.SplitSpec(levels=4, members=256, keep=0.5,
                                threshold=t, seed=s),
            chaos_components=C,
        )
        ests.append(doc["p"])
        if s == 0:
            # CIs overlap on a single run
            assert doc["ci_hi"] >= b_lo and b_hi >= doc["ci_lo"]
            assert doc["schema"] == "isotope-splitting/v1"
    # unbiased within tolerance over independent replicates
    assert abs(float(np.mean(ests)) - 0.3) < 0.04


def test_split_rare_event_resolved_within_budget():
    """True p ~ 1e-4 by construction; nonzero estimate at <= 10% of
    the ~10/p-member brute-force budget (the ISSUE acceptance bar)."""
    C = 6
    ev = _synthetic_eval(C)
    t = _mean_tail_quantile(C, 1e-4)
    doc = split_mod.subset_estimate(
        ev,
        split_mod.SplitSpec(levels=10, members=300, keep=0.2,
                            threshold=t, seed=5, chaos_prob=0.4),
        chaos_components=C,
    )
    assert doc["p"] > 0.0
    # within an order of magnitude of the constructed truth
    assert 1e-5 < doc["p"] < 1e-3
    assert doc["evaluations"] <= 0.1 * (10.0 / 1e-4)


def test_split_spec_parse_and_errors():
    s = split_mod.parse_split_spec(
        "levels=3,members=32,keep=0.25,threshold=0.5,sev=p99,"
        "slo=0.25,horizon=0.5,seed=9"
    )
    assert (s.levels, s.members, s.keep) == (3, 32, 0.25)
    assert s.severity == "p99" and s.slo_s == 0.25 and s.seed == 9
    assert split_mod.parse_split_spec("off") is None
    assert split_mod.parse_split_spec(None) is None
    with pytest.raises(ValueError, match="unknown splitting spec"):
        split_mod.parse_split_spec("levls=3")
    with pytest.raises(ValueError, match="survivor fraction"):
        split_mod.SplitSpec(keep=1.0)
    with pytest.raises(ValueError, match="members"):
        split_mod.SplitSpec(members=1)
    with pytest.raises(ValueError, match="severity"):
        split_mod.SplitSpec(severity="nope")


# -- per-member chaos schedules (resilience/faults.py) ----------------------


def test_chaos_jitter_deterministic_and_structure_preserving():
    reps = {"entry": 4, "worker": 4}
    chaos = (ChaosEvent("worker", 0.05, 0.12, replicas_down=1),
             ChaosEvent("entry", 0.10, 0.20))
    spec = faults.ChaosJitterSpec(
        time=0.4, magnitude=0.6, target=0.5, seed=3
    )
    es = faults.member_event_seeds(spec, 5, 2)
    a = faults.jitter_chaos_events(chaos, spec, es, reps)
    b = faults.jitter_chaos_events(chaos, spec, es, reps)
    assert a == b  # deterministic per member
    # same event count; cut multiset keeps the solo ORDER
    assert len(a) == 2
    solo_vals = sorted({0.05, 0.12, 0.10, 0.20})
    jit_vals = sorted({a[0].start_s, a[0].end_s,
                       a[1].start_s, a[1].end_s})
    rank = {v: i for i, v in enumerate(solo_vals)}
    assert jit_vals.index(a[0].start_s) == rank[0.05]
    assert jit_vals.index(a[1].end_s) == rank[0.20]
    for ev in a:
        assert ev.start_s < ev.end_s
        assert 1 <= ev.replicas_down <= reps[ev.service]
    # different members draw different schedules
    c = faults.jitter_chaos_events(
        chaos, spec, faults.member_event_seeds(spec, 6, 2), reps
    )
    assert c != a
    # identity spec leaves the schedule untouched
    ident = faults.jitter_chaos_events(
        chaos, faults.ChaosJitterSpec(),
        faults.member_event_seeds(faults.ChaosJitterSpec(), 5, 2),
        reps,
    )
    assert ident == chaos


def test_chaos_jitter_parse():
    s = faults.parse_chaos_jitter("time=0.2,mag=0.5,target=0.3,seed=7")
    assert (s.time, s.magnitude, s.target, s.seed) == (
        0.2, 0.5, 0.3, 7
    )
    assert faults.parse_chaos_jitter("off") is None
    with pytest.raises(ValueError, match="unknown chaos jitter"):
        faults.parse_chaos_jitter("tim=0.2")


@pytest.mark.slow
def test_member_chaos_identity_matches_plain_fleet(psim):
    """Per-member chaos OFF (and the identity jitter) = the PR 12
    fleet bit-for-bit: the traced chaos rows carry the same values the
    constants had."""
    spec = EnsembleSpec.of(2, mode="map")
    plain = psim.run_ensemble(OPEN, N, KEY, spec, block_size=BLOCK)
    ident = psim.run_ensemble(
        OPEN, N, KEY, spec, block_size=BLOCK,
        member_chaos=faults.ChaosJitterSpec(),
    )
    for f in ("count", "error_count", "latency_sum", "latency_hist"):
        assert np.array_equal(
            np.asarray(getattr(plain.summaries, f)),
            np.asarray(getattr(ident.summaries, f)),
        ), f
    assert ident.member_chaos == [CHAOS, CHAOS]


def test_member_chaos_member_matches_solo_schedule(psim, storm):
    """A member running an explicit jittered schedule is bit-equal to
    the solo Simulator built with that schedule."""
    _, compiled, pol = storm
    reps = {"entry": 4, "worker": 4}
    jit_events = faults.jitter_chaos_events(
        CHAOS, JITTER, faults.member_event_seeds(JITTER, 1, 1), reps
    )
    ens = psim.run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(2, mode="map"),
        block_size=BLOCK, member_chaos=[CHAOS, jit_events],
    )
    solo_sim = Simulator(
        compiled, SimParams(timeline=True), chaos=jit_events,
        policies=pol,
    )
    solo = solo_sim.run_summary(
        OPEN, N, jax.random.fold_in(KEY, 1), block_size=BLOCK
    )
    m = ens.member(1)
    assert np.array_equal(
        np.asarray(m.latency_hist), np.asarray(solo.latency_hist)
    )
    assert np.array_equal(
        np.asarray(m.error_count), np.asarray(solo.error_count)
    )


def test_member_chaos_rejections(storm):
    _, compiled, pol = storm
    # no chaos schedule to jitter — still a loud error
    nochaos = Simulator(compiled, SimParams(timeline=True),
                        policies=pol)
    with pytest.raises(ValueError, match="base chaos schedule"):
        nochaos.run_ensemble(
            OPEN, N, KEY, EnsembleSpec.of(2),
            member_chaos=faults.ChaosJitterSpec(time=0.1),
        )


def test_protected_carry_export_bit_equal(psim):
    """The run_policies_ensemble carry-I/O contract: exporting the
    member carry perturbs NOTHING (zero carry_in + block_offset 0 is
    bit-identical to the plain fleet), and the carry comes back as a
    member-stacked pytree a later segment (or a search rung) can
    resume from."""
    spec = EnsembleSpec.of(2, mode="map")
    kw = dict(block_size=BLOCK, window_s=WIN)
    plain = psim.run_policies_ensemble(OPEN, N, KEY, spec, **kw)
    ens, carry = psim.run_policies_ensemble(
        OPEN, N, KEY, spec, return_carry=True, **kw
    )
    for a, b in zip(jax.tree.leaves(plain.summaries),
                    jax.tree.leaves(ens.summaries)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(
        np.asarray(plain.policies.trips),
        np.asarray(ens.policies.trips),
    )
    leaves = jax.tree.leaves(carry)
    assert leaves
    assert all(np.asarray(x).shape[:1] == (2,) for x in leaves)
    # the export path keeps its preconditions loud
    with pytest.raises(ValueError, match="carry"):
        psim.run_policies_ensemble(
            OPEN, N, KEY, spec, trim=True, return_carry=True, **kw
        )


# -- universal member compositions (PR 18) ----------------------------------
#
# The four compositions the pre-universal member REJECTED (ungraceful
# kills, LB panic pools, saturated -qps max, rollout kill splits) now
# simulate — their tables became traced per-member arguments of the
# ONE member program.  Each pin: the composed fleet's member k is
# BIT-IDENTICAL to the solo Simulator built with member k's jittered
# schedule.

UNGRACEFUL = (ChaosEvent("worker", 0.1, 0.3, replicas_down=3,
                         drain=False),)
SAT = LoadModel(kind="closed", qps=None, connections=8)
REPS = {"entry": 4, "worker": 4}

LB_YAML = """
policies:
  worker:
    lb: {policy: least_request, panic_threshold: 50%}
"""

ROLLOUT_YAML = """
rollouts:
  defaults:
    gates: {min_samples: 20}
  worker:
    steps: [10%, 50%, 100%]
    bake: 2s
    rollback: {cooldown: 4s, max_retries: 1}
    canary: {error_rate: 30%}
"""

BASE_YAML = STORM.split("policies:")[0]


def _jittered(events, k):
    return faults.jitter_chaos_events(
        events, JITTER,
        faults.member_event_seeds(JITTER, k, len(events)), REPS,
    )


def _pin_member(stacked, solo, k, names=("latency_hist", "count")):
    for name in names:
        assert np.array_equal(
            np.asarray(getattr(stacked, name))[k],
            np.asarray(getattr(solo, name)),
        ), name


def test_chaos_x_ungraceful_member_matches_solo():
    """Ungraceful (drain: false) kill resets jitter per member."""
    c = compile_graph(ServiceGraph.from_yaml(BASE_YAML))
    jit = _jittered(UNGRACEFUL, 1)
    ens = Simulator(c, chaos=UNGRACEFUL).run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(2, mode="map"),
        block_size=BLOCK, member_chaos=[UNGRACEFUL, jit],
    )
    solo = Simulator(c, chaos=jit).run_summary(
        OPEN, N, jax.random.fold_in(KEY, 1), block_size=BLOCK
    )
    _pin_member(ens.summaries, solo, 1)


def test_chaos_x_lb_panic_member_matches_solo():
    """LB panic healthy-pool tables jitter per member."""
    from isotope_tpu.compiler import compile_lb

    g = ServiceGraph.from_yaml(BASE_YAML + LB_YAML)
    c = compile_graph(g)
    lbt = compile_lb(g, c)
    jit = _jittered(CHAOS, 1)
    ens = Simulator(c, chaos=CHAOS, lb=lbt).run_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(2, mode="map"),
        block_size=BLOCK, member_chaos=[CHAOS, jit],
    )
    solo = Simulator(c, chaos=jit, lb=lbt).run_summary(
        OPEN, N, jax.random.fold_in(KEY, 1), block_size=BLOCK
    )
    _pin_member(ens.summaries, solo, 1)


def test_chaos_x_saturated_member_matches_solo():
    """Finite-population (-qps max) MVA tables jitter per member."""
    c = compile_graph(ServiceGraph.from_yaml(BASE_YAML))
    jit = _jittered(CHAOS, 1)
    ens = Simulator(c, chaos=CHAOS).run_ensemble(
        SAT, N, KEY, EnsembleSpec.of(2, mode="map"),
        block_size=BLOCK, member_chaos=[CHAOS, jit],
    )
    solo = Simulator(c, chaos=jit).run_summary(
        SAT, N, jax.random.fold_in(KEY, 1), block_size=BLOCK
    )
    _pin_member(ens.summaries, solo, 1)


def test_chaos_x_rollout_member_matches_solo(storm):
    """Canary-first kill-split tables jitter per member — the rollout
    fleet composition the pre-universal member rejected outright."""
    from isotope_tpu.compiler import compile_rollouts

    g = ServiceGraph.from_yaml(STORM + ROLLOUT_YAML)
    c = compile_graph(g)
    pol = compile_policies(g, c)
    rt = compile_rollouts(g, c)
    jit = _jittered(CHAOS, 1)
    sim = Simulator(c, SimParams(timeline=True), chaos=CHAOS,
                    policies=pol, rollouts=rt)
    ens = sim.run_rollouts_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(2, mode="map"),
        block_size=BLOCK, trim=True, window_s=WIN,
        member_chaos=[CHAOS, jit],
    )
    solo_sim = Simulator(c, SimParams(timeline=True), chaos=jit,
                         policies=pol, rollouts=rt)
    solo = solo_sim.run_rollouts(
        OPEN, N, jax.random.fold_in(KEY, 1), block_size=BLOCK,
        trim=True, window_s=WIN,
    )
    _pin_member(ens.summaries, solo[0], 1)
    assert np.array_equal(
        np.asarray(ens.rollouts.weight)[1],
        np.asarray(solo[2].weight),
    )


def test_all_on_member_matches_solo():
    """Everything at once: policies + LB panic + rollout kill split +
    UNGRACEFUL member-jittered chaos in one fleet program."""
    from isotope_tpu.compiler import compile_lb, compile_rollouts

    all_on = STORM.replace(
        "  worker:\n    breaker:",
        "  worker:\n    lb: {policy: least_request, "
        "panic_threshold: 50%}\n    breaker:",
    ) + ROLLOUT_YAML
    g = ServiceGraph.from_yaml(all_on)
    c = compile_graph(g)
    pol = compile_policies(g, c)
    rt = compile_rollouts(g, c)
    lbt = compile_lb(g, c)
    jit = _jittered(UNGRACEFUL, 1)
    sim = Simulator(c, SimParams(timeline=True), chaos=UNGRACEFUL,
                    policies=pol, rollouts=rt, lb=lbt)
    ens = sim.run_rollouts_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(2, mode="map"),
        block_size=BLOCK, trim=True, window_s=WIN,
        member_chaos=[UNGRACEFUL, jit],
    )
    solo_sim = Simulator(c, SimParams(timeline=True), chaos=jit,
                         policies=pol, rollouts=rt, lb=lbt)
    solo = solo_sim.run_rollouts(
        OPEN, N, jax.random.fold_in(KEY, 1), block_size=BLOCK,
        trim=True, window_s=WIN,
    )
    _pin_member(ens.summaries, solo[0], 1)
    assert np.array_equal(
        np.asarray(ens.rollouts.weight)[1],
        np.asarray(solo[2].weight),
    )


def test_composed_sharded_matches_emulated():
    """The rollout x member-chaos composition agrees across the
    sharded device-mesh path and its emulated twin."""
    from isotope_tpu.compiler import compile_rollouts
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g = ServiceGraph.from_yaml(STORM + ROLLOUT_YAML)
    c = compile_graph(g)
    sh = ShardedSimulator(
        c, build_mesh(MeshSpec(data=2, svc=2)),
        SimParams(timeline=True), CHAOS,
        policies=compile_policies(g, c),
        rollouts=compile_rollouts(g, c),
    )
    spec = EnsembleSpec.of(4, mode="map")
    kw = dict(block_size=BLOCK, window_s=WIN, member_chaos=JITTER)
    a = sh.run_rollouts_ensemble(OPEN, N, KEY, spec, **kw)
    b = sh.run_rollouts_ensemble_emulated(OPEN, N, KEY, spec, **kw)
    assert np.array_equal(
        np.asarray(a.summaries.latency_hist),
        np.asarray(b.summaries.latency_hist),
    )
    assert np.array_equal(
        np.asarray(a.rollouts.weight),
        np.asarray(b.rollouts.weight),
    )


# -- protected fleets (engine) ----------------------------------------------


def test_protected_fleet_member_bit_equal_solo(psim, pfleet):
    solo = psim.run_policies(
        OPEN, N, jax.random.fold_in(KEY, 2), block_size=BLOCK,
        trim=True, window_s=WIN,
    )
    m = pfleet.member(2)
    tl = pfleet.member_timeline(2)
    pol = pfleet.member_policies(2)
    assert np.array_equal(
        np.asarray(m.latency_hist), np.asarray(solo[0].latency_hist)
    )
    assert np.array_equal(
        np.asarray(m.count), np.asarray(solo[0].count)
    )
    assert np.array_equal(
        np.asarray(tl.errors), np.asarray(solo[1].errors)
    )
    assert np.array_equal(
        np.asarray(tl.svc_busy_s), np.asarray(solo[1].svc_busy_s)
    )
    assert np.array_equal(
        np.asarray(pol.replicas), np.asarray(solo[2].replicas)
    )
    assert np.array_equal(
        np.asarray(pol.shed), np.asarray(solo[2].shed)
    )


def test_protected_fleet_severity_and_doc(pfleet):
    sev = pfleet.severity()
    assert sev.shape == (3,)
    doc = pfleet.to_doc("case", slo_s=10.0)
    assert doc["schema"] == "isotope-ensemble/v2"
    assert doc["protected"] is True
    assert doc["worst_member"] == int(np.argmax(sev))
    # Wilson-zero fix: with zero violations and a splitting block,
    # the slo dict reports the splitting estimate alongside
    fake_split = {"p": 3e-5, "ci_lo": 1e-5, "ci_hi": 9e-5}
    slo = pfleet.slo_violation(10.0, splitting=fake_split)
    assert slo["violations"] == 0
    assert slo["p_splitting"] == pytest.approx(3e-5)
    doc2 = pfleet.to_doc("case", slo_s=10.0, splitting=fake_split)
    assert doc2["splitting"]["p"] == pytest.approx(3e-5)
    assert "p_splitting" in doc2["slo"]
    from isotope_tpu.sim.ensemble import doc_member_quantiles

    assert doc_member_quantiles(doc).shape == (3, 3)


@pytest.mark.slow
def test_protected_fleet_vmap_matches_map(psim, pfleet):
    v = psim.run_policies_ensemble(
        OPEN, N, KEY, EnsembleSpec.of(3, mode="vmap"),
        block_size=BLOCK, trim=True, window_s=WIN,
    )
    assert np.array_equal(
        np.asarray(v.summaries.latency_hist),
        np.asarray(pfleet.summaries.latency_hist),
    )
    assert np.array_equal(
        np.asarray(v.policies.replicas),
        np.asarray(pfleet.policies.replicas),
    )


@pytest.mark.slow
@pytest.mark.slow
def test_sharded_protected_fleet_bit_equal_twin(storm):
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    _, compiled, pol = storm
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=2, svc=2)),
        SimParams(timeline=True), CHAOS, policies=pol,
    )
    spec = EnsembleSpec.of(4, mode="map")
    kw = dict(block_size=BLOCK, trim=True, window_s=WIN,
              member_chaos=JITTER)
    a = sh.run_policies_ensemble(OPEN, N, KEY, spec, **kw)
    b = sh.run_policies_ensemble_emulated(OPEN, N, KEY, spec, **kw)
    assert np.array_equal(
        np.asarray(a.summaries.latency_hist),
        np.asarray(b.summaries.latency_hist),
    )
    assert np.array_equal(
        np.asarray(a.timelines.errors), np.asarray(b.timelines.errors)
    )
    assert np.array_equal(
        np.asarray(a.policies.replicas),
        np.asarray(b.policies.replicas),
    )


# -- runner dispatch ---------------------------------------------------------


def test_runner_protected_fleet(tmp_path, storm):
    """The acceptance pin: --policies cases dispatch as fleets (no
    solo fallback), member 0 bit-equal to the pre-fleet solo protected
    run, worst-member postmortem stamped, splitting block attached."""
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import (
        _num_requests,
        _protected_window_block,
        run_experiment,
    )

    g, compiled, pol = storm
    topo = tmp_path / "storm.yaml"
    topo.write_text(STORM)
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(2_000.0,), connections=(8,), duration_s=2.0,
        load_kind="open", num_requests=4_000,
        policies=True, timeline_window_s=0.5,
        chaos=CHAOS,
        ensemble=3,
        ensemble_split=(
            "levels=2,members=6,keep=0.5,threshold=0.2,"
            "sev=err_share,horizon=0.5"
        ),
        ensemble_chaos_jitter="time=0.2,magnitude=0.4,seed=3",
    )
    (res,) = run_experiment(config, out_dir=str(tmp_path / "out"))
    assert not res.failed, res.error
    assert res.flat.get("_protected_fleet") is True
    assert res.flat.get("_policies") is True
    assert res.flat.get("_ensemble") == 3
    doc = res.ensemble
    assert doc["schema"] == "isotope-ensemble/v2"
    assert doc["member_chaos"] is True
    assert "splitting" in doc
    assert doc["splitting"]["schema"] == "isotope-splitting/v1"
    # worst-member postmortem stamps on the policy/timeline artifacts
    pol_doc = json.load(
        open(tmp_path / "out" / f"{res.label}.policies.json")
    )
    assert pol_doc["worst_member"] is True
    assert pol_doc["member"] == doc["worst_member"]
    assert pol_doc["fleet_members"] == 3
    assert "member_chaos" in pol_doc
    # member 0 rides the RUN key: bit-equal to the solo protected run
    # the pre-fleet runner would have executed (same window/block law)
    load = LoadModel(kind="open", qps=2_000.0, connections=8,
                     duration_s=2.0)
    sim = Simulator(
        compiled, SimParams(timeline=True), chaos=CHAOS, policies=pol
    )
    n = _num_requests(load, sim.capacity_qps(), 4_000)
    win, block = _protected_window_block(
        sim, load, sim.default_block_size(), config, None
    )
    run_key = jax.random.fold_in(jax.random.PRNGKey(0), 0)
    solo = sim.run_policies(
        load, n, run_key, block_size=block, trim=True, window_s=win
    )
    assert doc["member_counts"][0] == float(np.asarray(solo[0].count))
    assert doc["member_error_counts"][0] == float(
        np.asarray(solo[0].error_count)
    )


# -- vet rules ---------------------------------------------------------------


def test_vet_t024_split_lint():
    from isotope_tpu.analysis.topo_lint import lint_split

    assert lint_split(None) == []
    assert lint_split("levels=3,members=32,keep=0.25") == []
    bad = lint_split("levls=3")
    assert bad and bad[0].rule == "VET-T024"
    assert bad[0].severity == "error"
    few = lint_split("levels=3,members=2,keep=0.25")
    assert few and "survivor" in few[0].message
    # keep >= 1 is rejected at decode and surfaced as T024
    assert lint_split("keep=1.5")[0].rule == "VET-T024"


def test_vet_t025_protected_fleet_memory(psim):
    from types import SimpleNamespace

    from isotope_tpu.analysis import costmodel

    carry = costmodel.protected_carry_bytes(psim, 16, roll=False)
    assert carry > 0
    est = SimpleNamespace(
        capacity_bytes=1e6, peak_bytes_at_block=4e5
    )
    out = costmodel.protected_ensemble_findings(est, 8, carry)
    assert out and out[0].rule == "VET-T025"
    assert "carry" in out[0].message
    # fits -> no finding
    assert costmodel.protected_ensemble_findings(
        SimpleNamespace(capacity_bytes=1e12,
                        peak_bytes_at_block=1e3),
        2, carry,
    ) == []
    # carry-aware chunk is never larger than the carry-free one
    assert costmodel.ensemble_chunk(
        8, 4e5, 1e6, carry_bytes_per_member=carry
    ) <= costmodel.ensemble_chunk(8, 4e5, 1e6)
