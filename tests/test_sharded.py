"""Sharded-execution tests on the 8-device virtual CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.histogram import quantile_from_histogram
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.parallel import ShardedSimulator, default_mesh, make_mesh
from isotope_tpu.sim import LoadModel, SimParams, Simulator

YAML = """
defaults:
  responseSize: 1 KiB
services:
- name: entry
  isEntrypoint: true
  script:
  - - call: x
    - call: y
  - call: z
- name: x
  numReplicas: 2
- name: y
  script:
  - call: z
- name: z
"""
LOAD = LoadModel(kind="open", qps=2000.0)
KEY = jax.random.PRNGKey(11)


@pytest.fixture(scope="module")
def compiled():
    return compile_graph(ServiceGraph.from_yaml(YAML))


def test_eight_devices_available():
    assert jax.device_count() >= 8  # conftest forces the virtual mesh


def test_sharded_matches_single_device_statistics(compiled):
    n = 32768
    sharded = ShardedSimulator(compiled, make_mesh(4, 2))
    summary = sharded.run(LOAD, n, KEY)
    single = Simulator(compiled).run(LOAD, n, KEY)

    assert int(summary.count) == n
    # same offered load => identical analytic utilization
    np.testing.assert_allclose(
        summary.utilization, single.utilization, rtol=1e-6
    )
    # distributional agreement (different RNG streams)
    lat = np.asarray(single.client_latency)
    q_sharded = summary.quantiles_s((0.5, 0.99))
    q_single = np.quantile(lat, [0.5, 0.99])
    np.testing.assert_allclose(q_sharded, q_single, rtol=0.05)
    assert summary.mean_latency_s == pytest.approx(lat.mean(), rel=0.02)
    # every request executes every hop here (no probability/error gates)
    assert int(summary.hop_events) == n * compiled.num_hops


def test_sharded_deterministic(compiled):
    sharded = ShardedSimulator(compiled, make_mesh(4, 2))
    a = sharded.run(LOAD, 4096, KEY)
    b = sharded.run(LOAD, 4096, KEY)
    np.testing.assert_array_equal(a.latency_hist, b.latency_hist)
    np.testing.assert_array_equal(
        np.asarray(a.metrics.duration_hist), np.asarray(b.metrics.duration_hist)
    )


def test_svc_sharded_histograms_cover_all_services(compiled):
    mesh = make_mesh(4, 2)
    sharded = ShardedSimulator(compiled, mesh)
    summary = sharded.run(LOAD, 8192, KEY)
    dur = np.asarray(summary.metrics.duration_hist)
    # padded to a multiple of the svc axis, globally reassembled
    assert dur.shape[0] == sharded.s_pad >= compiled.num_services
    # every service served every request it saw: counts match incoming
    inc = np.asarray(summary.metrics.incoming_total)
    for s in range(compiled.num_services):
        assert dur[s].sum() == pytest.approx(inc[s])


def test_data_only_mesh(compiled):
    summary = ShardedSimulator(compiled, default_mesh()).run(LOAD, 8192, KEY)
    assert int(summary.count) == 8192
    assert float(summary.latency_min) > 0
    assert float(summary.latency_max) < 10.0


def test_closed_loop_sharded(compiled):
    summary = ShardedSimulator(compiled, make_mesh(4, 2)).run(
        LoadModel(kind="closed", qps=None, connections=16), 8192, KEY
    )
    assert int(summary.count) == 8192
    assert float(summary.error_count) == 0
    # throughput-driven offered load keeps the bottleneck busy but stable
    assert 0 < float(summary.utilization.max()) < 1.0


def test_closed_loop_connection_divisibility_enforced(compiled):
    sharded = ShardedSimulator(compiled, make_mesh(4, 2))
    with pytest.raises(ValueError):
        sharded.run(LoadModel(kind="closed", qps=100.0, connections=3), 64, KEY)


def test_quantile_from_histogram_accuracy():
    rng = np.random.default_rng(0)
    samples = rng.exponential(0.01, 100_000).astype(np.float32)
    from isotope_tpu.metrics.histogram import latency_histogram

    hist = np.asarray(latency_histogram(jnp.asarray(samples)))
    got = quantile_from_histogram(hist, [0.5, 0.9, 0.99])
    want = np.quantile(samples, [0.5, 0.9, 0.99])
    np.testing.assert_allclose(got, want, rtol=0.01)


# -- multi-slice (DCN axis) ------------------------------------------------


def test_multislice_mesh_shape():
    from isotope_tpu.parallel import make_multislice_mesh

    mesh = make_multislice_mesh(2, 2, 2)
    assert mesh.axis_names == ("slice", "data", "svc")
    assert dict(mesh.shape) == {"slice": 2, "data": 2, "svc": 2}
    with pytest.raises(ValueError):
        make_multislice_mesh(4, 4, 4)  # > 8 devices


def test_multislice_matches_single_slice(compiled):
    from isotope_tpu.parallel import make_multislice_mesh

    n = 16384
    multi = ShardedSimulator(compiled, make_multislice_mesh(2, 2, 2))
    flat = ShardedSimulator(compiled, make_mesh(4, 2))
    s_multi = multi.run(LOAD, n, KEY)
    s_flat = flat.run(LOAD, n, KEY)

    # same shard count => identical per-shard streams, identical merge
    assert multi.n_shards == flat.n_shards == 8
    assert int(s_multi.count) == int(s_flat.count) == n
    np.testing.assert_allclose(
        np.asarray(s_multi.latency_hist),
        np.asarray(s_flat.latency_hist),
    )
    np.testing.assert_allclose(
        float(s_multi.latency_sum), float(s_flat.latency_sum), rtol=1e-6
    )
    # per-service state is sharded over svc identically in both
    np.testing.assert_allclose(
        np.asarray(s_multi.metrics.duration_hist),
        np.asarray(s_flat.metrics.duration_hist),
    )


def test_multislice_closed_loop(compiled):
    from isotope_tpu.parallel import make_multislice_mesh

    load = LoadModel(kind="closed", qps=None, connections=16)
    sharded = ShardedSimulator(compiled, make_multislice_mesh(2, 2, 2))
    s = sharded.run(load, 4096, KEY)
    assert int(s.count) >= 4096
    single = Simulator(compiled).run(load, 4096, KEY)
    assert s.mean_latency_s == pytest.approx(
        float(single.client_latency.mean()), rel=0.05
    )


def test_svc_axis_required():
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices()[:4]).reshape(2, 2)
    bad = Mesh(devices, ("a", "b"))
    with pytest.raises(ValueError, match="svc"):
        ShardedSimulator(compile_graph(ServiceGraph.from_yaml(YAML)), bad)


@pytest.mark.slow
@pytest.mark.slow
def test_sharded_full_feature_agreement(compiled):
    # VERDICT r3 weak-6: nothing exercised closed-loop + chaos + churn
    # (+ the phased mTLS tax) through the sharded path.  The sharded
    # run must agree with the single-device engine distributionally —
    # same load, same phase machinery, every overlay active at once.
    from isotope_tpu.sim.config import ChaosEvent, MtlsSchedule, TrafficSplit

    chaos = (ChaosEvent(service="x", start_s=2.0, end_s=6.0,
                        replicas_down=1),)
    churn = (TrafficSplit(service="z", period_s=3.0,
                          weights=(1.0, 0.5)),)
    mtls = MtlsSchedule(period_s=4.0, taxes_s=(0.0, 5e-4))
    load = LoadModel(kind="closed", qps=3000.0, connections=64)
    n = 32_768

    single = Simulator(compiled, SimParams(), chaos, churn, mtls=mtls)
    res = single.run(load, n, KEY)
    lat_1 = np.asarray(res.client_latency, np.float64)

    sharded = ShardedSimulator(
        compiled, make_mesh(4, 2), SimParams(), chaos, churn, mtls=mtls
    )
    summary = sharded.run(load, n, KEY, block_size=4096)
    assert float(summary.count) >= n
    for q in (0.5, 0.99):
        got = quantile_from_histogram(
            np.asarray(summary.latency_hist), q
        )
        want = np.quantile(lat_1, q)
        assert got == pytest.approx(want, rel=0.05), (
            f"p{int(q * 100)}: sharded={got * 1e3:.3f}ms "
            f"single={want * 1e3:.3f}ms"
        )
    # the chaos phase and churn weights really applied: some error-free
    # traffic reduction shows in hop_events vs the no-overlay run
    plain = ShardedSimulator(compiled, make_mesh(4, 2))
    base = plain.run(LOAD, n, KEY, block_size=4096)
    assert float(summary.hop_events) < float(base.hop_events)
