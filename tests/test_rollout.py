"""Reactive canary rollouts (sim/rollout.py): decode, controller-law
semantics (promote / hold / rollback / retry exhaustion), engine co-sim,
chaos composition, sharded twin bit-equality, the protected-run
degradation ladder, runner artifacts, and the vet misconfiguration
rules."""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import (
    compile_graph,
    compile_policies,
    compile_rollouts,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.resilience import faults
from isotope_tpu.sim import rollout as roll_mod
from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator

KEY = jax.random.PRNGKey(0)

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 2
  script:
  - call: worker
- name: worker
  numReplicas: 2
"""

ROLLOUT = """
rollouts:
  defaults:
    gates: {min_samples: 20}
  worker:
    steps: [10%, 50%, 100%]
    bake: 2s
    rollback: {cooldown: 4s, max_retries: 1}
    canary: {error_rate: 30%}
"""


def graph_with(extra: str = ROLLOUT) -> ServiceGraph:
    return ServiceGraph.from_yaml(CHAIN + extra)


def tables_for(graph: ServiceGraph):
    return compile_rollouts(graph, compile_graph(graph))


def assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# -- decode / tables -------------------------------------------------------


def test_decode_defaults_and_percent_steps():
    g = graph_with()
    rset = roll_mod.RolloutSet.decode(g.rollouts, ["entry", "worker"])
    w = rset.for_service("worker")
    assert w.steps == (0.1, 0.5, 1.0)
    assert w.gates.min_samples == 20.0         # from defaults
    assert w.rollback.max_retries == 1
    assert w.canary.error_rate == pytest.approx(0.3)
    assert not rset.for_service("entry").active
    assert not rset.empty


def test_decode_rejects_bad_blocks():
    with pytest.raises(ValueError, match="unknown service"):
        roll_mod.RolloutSet.decode({"ghost": {}}, ["entry"])
    with pytest.raises(ValueError, match="unknown rollout fields"):
        roll_mod.RolloutSet.decode(
            {"entry": {"strategy": "blue-green"}}, ["entry"]
        )
    # defaults may not schedule the whole mesh
    with pytest.raises(ValueError, match="defaults may not declare"):
        roll_mod.RolloutSet.decode(
            {"defaults": {"steps": [0.5, 1.0]}}, ["entry"]
        )
    with pytest.raises(ValueError, match="lie in"):
        roll_mod.RolloutSet.decode(
            {"entry": {"steps": [0.0, 1.0]}}, ["entry"]
        )


def test_decode_errors_carry_key_paths():
    with pytest.raises(ValueError) as e:
        roll_mod.RolloutSet.decode(
            {"entry": {"rollback": {"cooldown": -1}}}, ["entry"]
        )
    assert "rollouts.entry.rollback" in str(e.value)


def test_build_tables_padding_and_kmax():
    g = graph_with("""
rollouts:
  worker:
    steps: [25%, 100%]
    canary: {replicas: 5, error_rate: 10%}
""")
    compiled = compile_graph(g)
    t = compile_rollouts(g, compiled)
    w = list(t.names).index("worker")
    e = list(t.names).index("entry")
    assert t.has_rollout[w] and not t.has_rollout[e]
    # steps right-pad with the final weight
    assert t.steps[w].tolist() == [0.25, 1.0]
    assert t.num_steps[w] == 2 and t.num_steps[e] == 0
    assert t.k_max == 5
    assert t.any_error_override
    assert "rollouts:" in t.signature()


def test_compile_rollouts_none_without_active_block():
    g = ServiceGraph.from_yaml(CHAIN)
    assert compile_rollouts(g, compile_graph(g)) is None
    # canary-only (no steps) entries never actuate -> None
    g2 = graph_with("""
rollouts:
  worker:
    canary: {error_rate: 10%}
""")
    assert compile_rollouts(g2, compile_graph(g2)) is None


# -- controller law (advance unit tests) -----------------------------------


def _unit_tables(steps=(0.1, 0.5, 1.0), bake=2.0, min_samples=20.0,
                 cooldown=4.0, retries=1, err_share=None):
    gates = {"min_samples": min_samples}
    if err_share is not None:
        gates["max_error_share"] = err_share
    rset = roll_mod.RolloutSet(
        per_service={
            "worker": roll_mod.ServiceRollout(
                steps=tuple(steps),
                bake_s=bake,
                gates=roll_mod.RolloutGates.decode(gates),
                rollback=roll_mod.RollbackPolicy(
                    cooldown_s=cooldown, max_retries=retries
                ),
            )
        },
        defaults=roll_mod.ServiceRollout(),
    )

    class _Svc:
        names = ("entry", "worker")
        error_rate = np.zeros(2)

    return roll_mod.build_tables(rset, _Svc())


def _spec(num_windows=8, window_s=1.0):
    class _Spec:
        pass

    s = _Spec()
    s.num_windows = num_windows
    s.window_s = window_s
    return s


def _obs(spec, cnt_b=100.0, cnt_c=50.0, err_b=0.0, err_c=0.0,
         lat_b=0.0, lat_c=0.0, ref_b=0.0, ref_c=0.0):
    """A synthetic (S=2, 2, W, 4) observation accumulator with uniform
    per-window signals on the worker row.  ``cnt_*`` are EXECUTED hops
    (channel 3); ``ref_*`` chaos-refused calls, which land in the
    arrival and error channels with no latency sample — exactly
    observe_block's accounting."""
    W = spec.num_windows
    obs = np.zeros((2, 2, W, 4), np.float32)
    cum = np.arange(1, W + 1, dtype=np.float32)
    obs[1, 0, :, 0] = (cnt_b + ref_b) * cum
    obs[1, 1, :, 0] = (cnt_c + ref_c) * cum
    obs[1, 0, :, 1] = (err_b + ref_b) * cum
    obs[1, 1, :, 1] = (err_c + ref_c) * cum
    obs[1, 0, :, 2] = lat_b * cum
    obs[1, 1, :, 2] = lat_c * cum
    obs[1, 0, :, 3] = cnt_b * cum
    obs[1, 1, :, 3] = cnt_c * cum
    # advance() reads per-window slices, not cumulative sums
    obs[:, :, 1:, :] = np.diff(obs, axis=2)
    return jnp.asarray(obs)


def test_advance_promotes_on_clean_bake():
    t = _unit_tables()
    dt = roll_mod.device_tables(t)
    spec = _spec()
    st = roll_mod.init_state(dt)
    obs = _obs(spec, cnt_b=100.0, cnt_c=50.0)
    st, delta = roll_mod.advance(st, dt, obs, jnp.float32(8.0), spec)
    promo = np.asarray(delta.promotions)[1]
    # bake=2 windows per step: promotes at windows 1, 3, 5 -> done
    assert promo.sum() == 3
    assert float(st.phase[1]) == roll_mod.PHASE_DONE
    assert float(st.weight[1]) == 1.0
    w = np.asarray(delta.weight)[1]
    assert w[0] == pytest.approx(0.1) and w[-1] == 1.0


def test_advance_holds_while_samples_short():
    t = _unit_tables(min_samples=1_000.0)
    dt = roll_mod.device_tables(t)
    spec = _spec()
    st = roll_mod.init_state(dt)
    obs = _obs(spec, cnt_b=100.0, cnt_c=50.0)
    st, delta = roll_mod.advance(st, dt, obs, jnp.float32(8.0), spec)
    assert np.asarray(delta.promotions)[1].sum() == 0
    assert np.asarray(delta.holds)[1].sum() > 0
    assert float(st.phase[1]) == roll_mod.PHASE_ROLLING
    assert float(st.weight[1]) == pytest.approx(0.1)  # still step 0


def test_advance_rolls_back_on_error_gate_and_cools_down():
    t = _unit_tables(retries=1)
    dt = roll_mod.device_tables(t)
    spec = _spec()
    st = roll_mod.init_state(dt)
    # canary error share 40% vs clean baseline: trips immediately once
    # min samples land (window 0)
    obs = _obs(spec, cnt_b=100.0, cnt_c=50.0, err_c=20.0)
    st, delta = roll_mod.advance(st, dt, obs, jnp.float32(8.0), spec)
    rb = np.asarray(delta.rollbacks)[1]
    assert rb[0] == 1.0                       # immediate trip
    # cooldown 4s -> restart at w5 -> trip again at w5+... second trip
    assert rb.sum() == 2.0
    assert float(st.phase[1]) == roll_mod.PHASE_FAILED
    assert float(st.weight[1]) == 0.0
    assert float(st.retries_left[1]) == -1.0


def test_advance_latency_gate_trips():
    t = _unit_tables(retries=0)
    dt = roll_mod.device_tables(t)
    spec = _spec()
    st = roll_mod.init_state(dt)
    # canary mean latency 3x baseline (ratio gate default 2.0)
    obs = _obs(spec, cnt_b=100.0, cnt_c=50.0, lat_b=100.0 * 0.01,
               lat_c=50.0 * 0.03)
    st, delta = roll_mod.advance(st, dt, obs, jnp.float32(8.0), spec)
    assert np.asarray(delta.rollbacks)[1].sum() == 1.0
    assert float(st.phase[1]) == roll_mod.PHASE_FAILED


def test_advance_latency_gate_undiluted_by_refused_calls():
    # a latency-regressed canary whose arm is ALSO partially chaos-
    # killed: the refused calls land in the arrival channel with zero
    # latency, but the mean divides by executed hops only — the 3x
    # regression must still trip the 2.0 ratio gate.  (Error gates are
    # disarmed so the refusals themselves can't cause the rollback.)
    t = _unit_tables(retries=0)
    t = dataclasses.replace(
        t,
        err_ratio=np.full_like(t.err_ratio, np.inf),
        err_share=np.full_like(t.err_share, np.inf),
    )
    dt = roll_mod.device_tables(t)
    spec = _spec()
    st = roll_mod.init_state(dt)
    # 50 executed canary hops/window at mean 0.03 s + 200 refusals:
    # a diluted mean (1.5/250 = 0.006 s) would pass the 2 x 0.01 s bar
    obs = _obs(spec, cnt_b=100.0, cnt_c=50.0, lat_b=100.0 * 0.01,
               lat_c=50.0 * 0.03, ref_c=200.0)
    st, delta = roll_mod.advance(st, dt, obs, jnp.float32(8.0), spec)
    assert np.asarray(delta.rollbacks)[1].sum() == 1.0
    assert float(st.phase[1]) == roll_mod.PHASE_FAILED


def test_advance_cooldown_expiry_restarts_schedule():
    t = _unit_tables(retries=1, cooldown=2.0)
    dt = roll_mod.device_tables(t)
    spec = _spec(num_windows=4)
    st = roll_mod.init_state(dt)
    bad = _obs(spec, cnt_b=100.0, cnt_c=50.0, err_c=25.0)
    st, delta = roll_mod.advance(st, dt, bad, jnp.float32(1.0), spec)
    assert float(st.phase[1]) == roll_mod.PHASE_COOLDOWN
    assert float(st.weight[1]) == 0.0
    # clean windows after the trip: cooldown burns, schedule restarts
    clean = _obs(spec, cnt_b=100.0, cnt_c=0.0)
    st, delta = roll_mod.advance(st, dt, clean, jnp.float32(4.0), spec)
    assert float(st.phase[1]) == roll_mod.PHASE_ROLLING
    assert float(st.step[1]) == 0.0
    assert float(st.weight[1]) == pytest.approx(0.1)


def test_advance_ignores_incomplete_and_stale_windows():
    t = _unit_tables()
    dt = roll_mod.device_tables(t)
    spec = _spec()
    st = roll_mod.init_state(dt)
    obs = _obs(spec, cnt_b=100.0, cnt_c=50.0)
    # only windows strictly before t_complete advance the clocks
    st1, d1 = roll_mod.advance(st, dt, obs, jnp.float32(2.0), spec)
    assert int(st1.last_window) == 1
    assert np.asarray(d1.windows_done).sum() == 2
    # replaying the same accumulator advances nothing new
    st2, d2 = roll_mod.advance(st1, dt, obs, jnp.float32(2.0), spec)
    assert int(st2.last_window) == 1
    assert np.asarray(d2.windows_done).sum() == 0
    assert_tree_equal(st1, st2)


# -- engine co-sim ---------------------------------------------------------


@pytest.fixture(scope="module")
def canary_case():
    g = graph_with()
    compiled = compile_graph(g)
    return g, compiled, compile_rollouts(g, compiled)


def test_rollouts_off_byte_identical(canary_case):
    """A Simulator CARRYING rollout tables must trace byte-identical
    plain programs (the tables only matter through run_rollouts)."""
    g, compiled, tables = canary_case
    load = LoadModel(kind="open", qps=500.0)
    params = SimParams(timeline=True)
    plain = Simulator(compiled, params)
    carrying = Simulator(compiled, params, rollouts=tables)
    r_plain = plain.run(load, 2_000, KEY)
    r_roll = carrying.run(load, 2_000, KEY)
    assert_tree_equal(r_plain, r_roll)
    t_plain = plain.run_timeline(load, 2_000, KEY, block_size=1_024,
                                 window_s=1.0)
    t_roll = carrying.run_timeline(load, 2_000, KEY, block_size=1_024,
                                   window_s=1.0)
    assert_tree_equal(t_plain, t_roll)


def test_bad_canary_rolls_back_within_bake(canary_case):
    g, compiled, tables = canary_case
    sim = Simulator(compiled, SimParams(timeline=True),
                    rollouts=tables)
    load = LoadModel(kind="open", qps=500.0)
    s, tl, roll = sim.run_rollouts(
        load, 8_000, KEY, block_size=1_000, window_s=1.0
    )
    doc = roll_mod.to_doc(compiled, roll, tables)
    w = doc["services"]["worker"]
    # detected and reverted inside the first bake (2s), retried once,
    # reverted again -> failed at weight 0
    assert w["rollbacks"] == 2.0
    assert w["rollback_onsets_s"][0] <= 2.0
    assert w["state"] == "failed"
    assert w["final_weight"] == 0.0
    # the per-arm channel reconciles with the recorder's totals
    ver = np.asarray(roll.ver_arrivals)
    hop = np.asarray(tl.svc_arrivals)
    np.testing.assert_allclose(ver.sum(axis=1), hop, rtol=1e-5)


def test_clean_canary_promotes_to_done(canary_case):
    g, compiled, _ = canary_case
    g2 = graph_with("""
rollouts:
  worker:
    steps: [10%, 50%, 100%]
    bake: 2s
    gates: {min_samples: 20}
""")
    tables = compile_rollouts(g2, compiled)
    sim = Simulator(compiled, SimParams(timeline=True),
                    rollouts=tables)
    s, tl, roll = sim.run_rollouts(
        LoadModel(kind="open", qps=500.0), 8_000, KEY,
        block_size=1_000, window_s=1.0,
    )
    doc = roll_mod.to_doc(compiled, roll, tables)
    w = doc["services"]["worker"]
    assert w["state"] == "done"
    assert w["final_weight"] == 1.0
    assert w["promotions"] == 3.0
    assert w["rollbacks"] == 0.0
    assert roll_mod.format_table(doc)  # renders


def test_rollout_requires_tables_timeline_and_paced_load(canary_case):
    g, compiled, tables = canary_case
    load = LoadModel(kind="open", qps=500.0)
    with pytest.raises(ValueError, match="rollout tables"):
        Simulator(compiled, SimParams(timeline=True)).run_rollouts(
            load, 1_000, KEY
        )
    with pytest.raises(ValueError, match="timeline"):
        Simulator(compiled, SimParams(), rollouts=tables).run_rollouts(
            load, 1_000, KEY
        )
    with pytest.raises(ValueError, match="saturated"):
        Simulator(
            compiled, SimParams(timeline=True), rollouts=tables
        ).run_rollouts(
            LoadModel(kind="closed", qps=None, connections=8),
            1_000, KEY,
        )


def test_canary_kill_composes_with_policies(canary_case):
    """The chaos-composed scenario: a kill on the rolled-out service
    takes the canary replicas first, the gate trips on the canary's
    transport failures, the rollout reverts, and the PR 9 autoscaler
    recovers the baseline arm — all in one carry."""
    g = graph_with("""
policies:
  worker:
    autoscaler: {min_replicas: 2, max_replicas: 6,
                 target_utilization: 50%, sync_period: 1s,
                 stabilization_window: 20s}
rollouts:
  worker:
    steps: [20%, 100%]
    bake: 3s
    gates: {min_samples: 20, max_error_share: 10%}
    rollback: {cooldown: 30s, max_retries: 0}
""")
    compiled = compile_graph(g)
    rtables = compile_rollouts(g, compiled)
    ptables = compile_policies(g, compiled)
    chaos = (ChaosEvent(service="worker", start_s=2.0, end_s=5.0,
                        replicas_down=1),)
    sim = Simulator(compiled, SimParams(timeline=True), chaos,
                    policies=ptables, rollouts=rtables)
    s, tl, roll, pol = sim.run_rollouts(
        LoadModel(kind="open", qps=800.0), 10_000, KEY,
        block_size=800, window_s=1.0,
    )
    doc = roll_mod.to_doc(compiled, roll, rtables)
    w = doc["services"]["worker"]
    # the canary-first kill downs the single canary pod; its transport
    # errors trip the absolute error gate during the chaos window
    assert w["rollbacks"] == 1.0
    assert 2.0 <= w["rollback_onsets_s"][0] <= 6.0
    assert w["state"] == "failed"
    # the policy loop rode the same carry (series present and sane)
    reps = np.asarray(pol.replicas)[list(tables_names(rtables)).index(
        "worker"
    )]
    assert reps.min() >= 0.0


def tables_names(t):
    return t.names


# -- sharded twin ----------------------------------------------------------


@pytest.mark.slow
@pytest.mark.slow
@pytest.mark.slow
def test_sharded_rollouts_bit_equal_to_emulated_twin(canary_case):
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g, compiled, tables = canary_case
    params = SimParams(timeline=True, timeline_window_s=1.0)
    load = LoadModel(kind="closed", qps=400.0, connections=8)
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=4, svc=1)), params,
        rollouts=tables,
    )
    args = dict(block_size=800, window_s=1.0)
    dev = sh.run_rollouts(load, 4_000, KEY, **args)
    emu = sh.run_rollouts_emulated(load, 4_000, KEY, **args)
    assert len(dev) == len(emu) == 3
    assert_tree_equal(dev, emu)
    # the trip happened on the merged trajectory
    assert np.asarray(dev[2].rollbacks).sum() >= 1.0


@pytest.mark.slow
@pytest.mark.slow
@pytest.mark.slow
def test_sharded_protected_attribution_bit_equal(canary_case):
    """ROADMAP open item (c): the sharded protected run reduces blame
    with the run_attributed collectives, bit-equal to the emulated
    twin's host merge."""
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g, compiled, tables = canary_case
    ptables = compile_policies(ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    breaker: {max_pending: 50}
"""), compiled)
    params = SimParams(timeline=True, attribution=True)
    load = LoadModel(kind="closed", qps=400.0, connections=8)
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=4, svc=1)), params,
        policies=ptables, rollouts=tables,
    )
    args = dict(block_size=800, window_s=1.0, attribution=True)
    dev = sh.run_rollouts(load, 4_000, KEY, **args)
    emu = sh.run_rollouts_emulated(load, 4_000, KEY, **args)
    assert len(dev) == len(emu) == 5  # summary, tl, roll, pol, attr
    assert_tree_equal(dev, emu)
    attr = dev[-1]
    assert float(np.asarray(attr.count)) > 0
    # policies-only protected attribution merges the same way
    pdev = sh.run_policies(load, 4_000, KEY, **args)
    pemu = sh.run_policies_emulated(load, 4_000, KEY, **args)
    assert len(pdev) == len(pemu) == 4
    assert_tree_equal(pdev, pemu)


def test_sharded_rollouts_reject_svc_mesh(canary_case):
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g, compiled, tables = canary_case
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=4, svc=2)),
        SimParams(timeline=True), rollouts=tables,
    )
    with pytest.raises(ValueError, match="svc=1"):
        sh.run_rollouts(
            LoadModel(kind="open", qps=500.0), 1_024, KEY
        )


@pytest.mark.slow
@pytest.mark.slow
@pytest.mark.slow
def test_emulated_mesh_rollout_twin_runs(canary_case):
    from isotope_tpu.parallel import MeshSpec, ShardedSimulator
    from isotope_tpu.parallel.mesh import EmulatedMesh

    g, compiled, tables = canary_case
    sh = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=2, svc=1, slices=2)),
        SimParams(timeline=True, timeline_window_s=1.0),
        rollouts=tables,
    )
    load = LoadModel(kind="open", qps=500.0)
    s, tl, roll = sh.run_rollouts_emulated(
        load, 4_096, KEY, block_size=1_024, window_s=1.0
    )
    assert float(s.count) >= 4_096
    assert np.asarray(roll.rollbacks).sum() >= 1.0


# -- protected-run degradation ladder --------------------------------------


def test_protected_ladder_degrades_and_records(canary_case, tmp_path):
    """ROADMAP open item (d): a protected-run OOM walks the supervisor
    ladder (half-block next) instead of failing the case."""
    from isotope_tpu.metrics.prometheus import MetricsCollector
    from isotope_tpu.resilience import ResiliencePolicy
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import _protected_run

    g, compiled, tables = canary_case
    sim = Simulator(compiled, SimParams(timeline=True),
                    rollouts=tables)
    load = LoadModel(kind="open", qps=500.0, duration_s=8.0)
    config = ExperimentConfig(
        topology_paths=("x.yaml",),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(500.0,), connections=(8,), duration_s=8.0, rollouts=True,
    )
    policy = ResiliencePolicy(max_retries=0, degrade=True)
    try:
        faults.install("oom:engine.run:1")
        out = _protected_run(
            sim, None, False, load, 4_000, KEY, 65_536, config,
            MetricsCollector(compiled), policy, None, None, tables,
        )
    finally:
        faults.install("")
    (summary, tl, roll, pol, blame, attr, degraded_to) = out
    assert degraded_to == "half-block"
    assert pol is None and roll is not None
    assert np.asarray(roll.rollbacks).sum() >= 1.0


def test_protected_ladder_propagates_with_degrade_off(canary_case):
    from isotope_tpu.metrics.prometheus import MetricsCollector
    from isotope_tpu.resilience import ResiliencePolicy
    from isotope_tpu.resilience.faults import InjectedFault
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import _protected_run

    g, compiled, tables = canary_case
    sim = Simulator(compiled, SimParams(timeline=True),
                    rollouts=tables)
    load = LoadModel(kind="open", qps=500.0, duration_s=8.0)
    config = ExperimentConfig(
        topology_paths=("x.yaml",),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(500.0,), connections=(8,), duration_s=8.0, rollouts=True,
    )
    policy = ResiliencePolicy(max_retries=0, degrade=False)
    try:
        faults.install("oom:engine.run:1")
        with pytest.raises(InjectedFault):
            _protected_run(
                sim, None, False, load, 4_000, KEY, 65_536, config,
                MetricsCollector(compiled), policy, None, None,
                tables,
            )
    finally:
        faults.install("")


# -- runner artifacts ------------------------------------------------------


def test_runner_rollout_artifact_round_trip(tmp_path, canary_case):
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import run_experiment

    g, _, _ = canary_case
    topo = tmp_path / "canary.yaml"
    topo.write_text(g.to_yaml())
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(500.0,),
        connections=(8,),
        duration_s=8.0,
        load_kind="open",
        num_requests=4_000,
        rollouts=True,
        timeline_window_s=1.0,
    )
    (res,) = run_experiment(config, out_dir=str(tmp_path / "out"))
    assert not res.failed
    assert res.rollouts is not None
    assert res.rollouts["schema"] == "isotope-rollout/v1"
    assert res.timeline is not None
    assert res.flat.get("_rollout") is True
    path = tmp_path / "out" / f"{res.label}.rollout.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    w = doc["services"]["worker"]
    assert w["rollbacks"] >= 1.0
    assert w["rollback_onsets_s"]


# -- vet rules -------------------------------------------------------------


def test_vet_rollout_rules():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = ServiceGraph.from_yaml(CHAIN + """
rollouts:
  worker:
    steps: [25%, 10%, 80%]
    bake: 2s
""")
    rules = {f.rule for f in lint_graph(
        g, params=SimParams(timeline_window_s=10.0)
    )}
    assert "VET-T015" in rules   # non-monotone AND not ending at 100%
    assert "VET-T016" in rules   # bake 2s < window 10s


def test_vet_rollout_canary_without_steps():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = ServiceGraph.from_yaml(CHAIN + """
rollouts:
  worker:
    canary: {error_rate: 10%}
""")
    fs = [f for f in lint_graph(g) if f.rule == "VET-T018"]
    assert len(fs) == 1
    assert "never actuates" in fs[0].message


def test_vet_rollout_decode_error_is_t015():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = ServiceGraph.from_yaml(CHAIN)
    g.rollouts = {"worker": {"steps": "everything"}}
    fs = [f for f in lint_graph(g) if f.rule == "VET-T015"]
    assert len(fs) == 1 and fs[0].severity == "error"


def test_vet_rollout_min_samples_unreachable(tmp_path):
    from isotope_tpu.analysis.topo_lint import lint_config
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )

    topo = tmp_path / "t.yaml"
    topo.write_text(CHAIN + """
rollouts:
  worker:
    steps: [1%, 100%]
    bake: 2s
    gates: {min_samples: 500}
""")
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(100.0,), connections=(8,), duration_s=30.0,
        load_kind="open", rollouts=True,
    )
    fs, _ = lint_config(config)
    assert any(f.rule == "VET-T017" for f in fs)


def test_vet_clean_rollout_no_findings():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = graph_with("""
rollouts:
  worker:
    steps: [10%, 50%, 100%]
    bake: 12s
    gates: {min_samples: 20}
""")
    rollout_rules = {
        f.rule for f in lint_graph(g)
        if f.rule in ("VET-T015", "VET-T016", "VET-T017", "VET-T018")
    }
    assert not rollout_rules
