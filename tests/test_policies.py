"""In-graph resilience policies (sim/policies.py): decode, control-law
semantics, engine co-sim, sharded twin bit-equality, feedback budget,
chaos-site interplay, and the vet misconfiguration rules."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph, compile_policies
from isotope_tpu.metrics import timeline as timeline_mod
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.resilience import faults
from isotope_tpu.sim import policies as pol_mod
from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator

KEY = jax.random.PRNGKey(0)
MU = 13_000.0

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 4
  script:
  - call: {service: worker, timeout: 850us, retries: 2}
- name: worker
  numReplicas: 4
"""

POLICIES = """
policies:
  defaults:
    retry_budget: {budget_percent: 25%}
  worker:
    breaker: {max_pending: 6, max_connections: 64,
              consecutive_errors: 5, base_ejection: 2s}
    autoscaler: {min_replicas: 2, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s}
"""


def graph_with_policies(extra: str = POLICIES) -> ServiceGraph:
    return ServiceGraph.from_yaml(CHAIN + extra)


def tables_for(graph: ServiceGraph):
    return compile_policies(graph, compile_graph(graph))


# -- decode / tables -------------------------------------------------------


def test_decode_defaults_and_override():
    g = graph_with_policies()
    pset = pol_mod.PolicySet.decode(g.policies, ["entry", "worker"])
    # defaults seed every service
    assert pset.for_service("entry").retry_budget.budget_percent == 0.25
    w = pset.for_service("worker")
    assert w.retry_budget.budget_percent == 0.25  # inherited
    assert w.breaker.max_pending == 6
    assert w.autoscaler.max_replicas == 8


def test_decode_explicit_null_disables_default():
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  defaults:
    retry_budget: {budget_percent: 10%}
  worker:
    retry_budget: null
""")
    pset = pol_mod.PolicySet.decode(g.policies, ["entry", "worker"])
    assert pset.for_service("worker").retry_budget is None
    assert pset.for_service("entry").retry_budget is not None


def test_decode_unknown_service_and_fields():
    with pytest.raises(ValueError, match="unknown service"):
        pol_mod.PolicySet.decode({"ghost": {}}, ["entry"])
    with pytest.raises(ValueError, match="unknown policy fields"):
        pol_mod.PolicySet.decode(
            {"entry": {"bulkhead": {}}}, ["entry"]
        )


def test_decode_errors_carry_key_paths():
    with pytest.raises(ValueError) as e:
        pol_mod.PolicySet.decode(
            {"entry": {"breaker": {"max_pending": -1}}}, ["entry"]
        )
    assert "policies.entry.breaker" in str(e.value)


def test_build_tables_sentinels_and_kmax():
    g = graph_with_policies()
    t = tables_for(g)
    assert t is not None and t.any_breaker and t.any_budget and t.any_hpa
    names = list(t.names)
    w = names.index("worker")
    e = names.index("entry")
    assert np.isinf(t.max_pending[e])       # no breaker on entry
    assert t.max_pending[w] == 6
    assert t.has_budget.all()               # default applies everywhere
    assert t.k_max == 8                     # autoscaler max wins over 4
    assert "policies:" in t.signature()


def test_build_tables_rejects_empty_autoscaler_range():
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    autoscaler: {min_replicas: 6, max_replicas: 2}
""")
    with pytest.raises(ValueError, match="min_replicas"):
        tables_for(g)


def test_compile_policies_none_without_block():
    g = ServiceGraph.from_yaml(CHAIN)
    assert compile_policies(g, compile_graph(g)) is None


def test_policies_round_trips_through_encode():
    g = graph_with_policies()
    again = ServiceGraph.decode(g.encode())
    assert again.policies == g.policies


# -- byte-identity / neutrality pins ---------------------------------------


def test_policies_off_byte_identical():
    """The acceptance pin: a Simulator WITHOUT policy tables (the
    default) and one CARRYING tables trace the same plain-run program —
    run_summary outputs are bit-equal leaf by leaf.  Both sides share
    the DEFAULT bucketed plan: the bucket planner no longer depends on
    policy-table presence (the retry-budget gate reached the scan
    body, sim/levelscan.py)."""
    g = graph_with_policies()
    compiled = compile_graph(g)
    params = SimParams()
    load = LoadModel(kind="open", qps=2_000.0)
    a = Simulator(compiled, params).run_summary(
        load, 4_096, KEY, block_size=1_024
    )
    b = Simulator(
        compiled, params, policies=tables_for(g)
    ).run_summary(load, 4_096, KEY, block_size=1_024)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_policies_default_keeps_bucketed_plan():
    """policies=None must not change the default executor: the bucket
    plan stays whatever SimParams asked for."""
    from isotope_tpu.compiler.buckets import ScanBucketPlan

    yaml_text = "services:\n- name: a\n  isEntrypoint: true\n  script:\n"
    yaml_text += "  - call: b\n- name: b\n  script: [{call: c}]\n- name: c\n"
    compiled = compile_graph(ServiceGraph.from_yaml(yaml_text))
    sim = Simulator(compiled, SimParams())
    assert any(isinstance(p, ScanBucketPlan) for p in sim._plan)


def _assert_ulp_equal(a, b, maxulp=1):
    """Exact on integer/bool leaves, <= ``maxulp`` on float leaves —
    the jit-twin tolerance the levelscan/overlap pins use (XLA may
    contract the policy path's extra neutral multiplies into FMAs,
    shifting intermediate rounding by 1 ULP)."""
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        x, y = np.asarray(x), np.asarray(y)
        if np.issubdtype(x.dtype, np.floating):
            np.testing.assert_array_max_ulp(x, y, maxulp=maxulp)
        else:
            assert np.array_equal(x, y)


@pytest.mark.slow
def test_neutral_policies_match_unpoliced_run():
    """A policy set that never actuates (huge caps, budget slack, HPA
    pinned at the static count) must leave the protected run's summary
    AND timeline equal to run_timeline on the same simulator (exact on
    counts, <= 1 ULP on float reductions)."""
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    breaker: {max_pending: 1000000, max_connections: 1000000}
    retry_budget: {budget_percent: 100%, min_retries_concurrent: 1000000}
    autoscaler: {min_replicas: 4, max_replicas: 4, target_utilization: 60%,
                 sync_period: 1s}
""")
    compiled = compile_graph(g)
    params = SimParams(timeline=True, timeline_window_s=0.5)
    sim = Simulator(compiled, params, policies=tables_for(g))
    load = LoadModel(kind="open", qps=2_000.0)
    s_pol, tl_pol, pol = sim.run_policies(
        load, 4_096, KEY, block_size=1_024, window_s=0.5
    )
    s_tl, tl_plain = sim.run_timeline(
        load, 4_096, KEY, block_size=1_024, window_s=0.5
    )
    _assert_ulp_equal(s_pol, s_tl)
    _assert_ulp_equal(tl_pol, tl_plain)
    # and the actuation series shows no action
    assert float(np.asarray(pol.trips).sum()) == 0
    assert float(np.asarray(pol.scale_events).sum()) == 0
    done = np.asarray(pol.windows_done) > 0
    assert (np.asarray(pol.replicas)[1][done] == 4).all()


def test_run_policies_requires_tables_timeline_and_rejects_sat():
    g = graph_with_policies()
    compiled = compile_graph(g)
    t = tables_for(g)
    load = LoadModel(kind="open", qps=500.0)
    with pytest.raises(ValueError, match="policy tables"):
        Simulator(compiled, SimParams(timeline=True)).run_policies(
            load, 256, KEY
        )
    with pytest.raises(ValueError, match="timeline"):
        Simulator(compiled, SimParams(), policies=t).run_policies(
            load, 256, KEY
        )
    sat = LoadModel(kind="closed", qps=None, connections=8)
    with pytest.raises(ValueError, match="-qps max"):
        Simulator(
            compiled, SimParams(timeline=True), policies=t
        ).run_policies(sat, 256, KEY)


# -- breaker / budget physics ----------------------------------------------


def _forced_fx(tables, shed=None, allow=None, replicas=None):
    S = tables.num_services
    return pol_mod.PolicyFx(
        replicas=(
            jnp.asarray(replicas, jnp.float32)
            if replicas is not None
            else jnp.asarray(tables.static_replicas, jnp.float32)
        ),
        shed=(
            jnp.asarray(shed, jnp.float32)
            if shed is not None
            else jnp.zeros(S, jnp.float32)
        ),
        retry_allow=(
            jnp.asarray(allow, jnp.float32)
            if allow is not None
            else jnp.ones(S, jnp.float32)
        ),
    )


def _core(sim, n, fx, qps=1_000.0):
    c = 1
    res, _, _ = sim._simulate_core(
        n, "open", 0, KEY, jnp.float32(qps), jnp.float32(0.0),
        jnp.float32(qps), jnp.float32(0.0), jnp.float32(0.0),
        jnp.zeros((c,), jnp.float32), jnp.float32(0.0),
        policy_fx=fx,
    )
    return res


def test_breaker_shed_takes_error_path_not_queue():
    g = graph_with_policies()
    compiled = compile_graph(g)
    sim = Simulator(
        compiled,
        SimParams(timeline=True, service_time="deterministic"),
        policies=tables_for(g),
    )
    w = list(compiled.services.names).index("worker")
    shed = np.zeros(compiled.num_services)
    shed[w] = 1.0
    res = _core(sim, 512, _forced_fx(sim._policies, shed=shed))
    worker_hops = compiled.hop_service == w
    sent = np.asarray(res.hop_sent)[:, worker_hops]
    err = np.asarray(res.hop_error)[:, worker_hops]
    lat = np.asarray(res.hop_latency)[:, worker_hops]
    assert sent.any()
    # every executed worker hop 500s fast: no wait, no script — the
    # deterministic service time is the whole server-side latency
    assert (err == sent).all()
    np.testing.assert_allclose(
        lat[sent], sim.params.cpu_time_s, rtol=1e-5
    )
    # a downstream 500 does not fail the caller
    assert not np.asarray(res.client_error).any()


def test_breaker_shed_on_entry_fails_clients():
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  entry:
    breaker: {max_pending: 1}
""")
    compiled = compile_graph(g)
    sim = Simulator(
        compiled, SimParams(timeline=True), policies=tables_for(g)
    )
    shed = np.zeros(compiled.num_services)
    shed[compiled.entry_service] = 1.0
    res = _core(sim, 256, _forced_fx(sim._policies, shed=shed))
    assert np.asarray(res.client_error).all()


def test_budget_zero_truncates_attempt_fan():
    """Under a timeout storm (3 of 4 replicas down, waits far past the
    850us call timeout) retries fire on nearly every request;
    retry_allow=0 suppresses every attempt past the first, and the
    suppressed retry surfaces the prior attempt's failure."""
    g = graph_with_policies()
    compiled = compile_graph(g)
    chaos = (ChaosEvent(service="worker", start_s=0.0, end_s=1e9,
                        replicas_down=3),)
    sim = Simulator(
        compiled, SimParams(timeline=True), chaos,
        policies=tables_for(g),
    )
    qps = 0.325 * 4 * MU
    retry_hops = compiled.hop_attempt > 0
    res_open = _core(sim, 512, _forced_fx(sim._policies), qps=qps)
    assert np.asarray(res_open.hop_sent)[:, retry_hops].sum() > 0
    res_cap = _core(
        sim, 512,
        _forced_fx(sim._policies, allow=np.zeros(compiled.num_services)),
        qps=qps,
    )
    assert np.asarray(res_cap.hop_sent)[:, retry_hops].sum() == 0
    # the suppressed retry surfaces the prior attempt's failure —
    # at least as many client errors, reached in ~1/3 the time (one
    # timeout instead of three serial ones)
    assert (
        np.asarray(res_cap.client_error).sum()
        >= np.asarray(res_open.client_error).sum()
    )
    assert (
        float(np.asarray(res_cap.client_latency).mean())
        < float(np.asarray(res_open.client_latency).mean())
    )


def test_dynamic_replicas_change_wait_law():
    """Halving the policy replica count must lengthen waits (the
    dynamic count reaches queueing.mmk_params)."""
    g = graph_with_policies()
    compiled = compile_graph(g)
    sim = Simulator(
        compiled, SimParams(timeline=True), policies=tables_for(g)
    )
    qps = 0.6 * 4 * MU
    full = _core(sim, 4_096, _forced_fx(sim._policies), qps=qps)
    halved = _core(
        sim, 4_096,
        _forced_fx(sim._policies, replicas=np.asarray([4.0, 1.0])),
        qps=qps,
    )
    assert (
        float(np.asarray(halved.hop_latency).mean())
        > float(np.asarray(full.hop_latency).mean())
    )


# -- control law (advance) -------------------------------------------------


def _mini_tables(extra: str):
    g = ServiceGraph.from_yaml(CHAIN + extra)
    compiled = compile_graph(g)
    return compiled, tables_for(g)


def _tl_with(spec, S, busy=None, inflight=None, errors=None):
    tl = timeline_mod.zeros_summary(
        timeline_mod.TimelineSpec(
            num_windows=spec[0], window_s=spec[1], num_services=S,
            hop_service=jnp.zeros(1, jnp.int32),
        )
    )
    rep = {}
    if busy is not None:
        rep["svc_busy_s"] = jnp.asarray(busy, jnp.float32)
    if inflight is not None:
        rep["svc_inflight_s"] = jnp.asarray(inflight, jnp.float32)
    if errors is not None:
        rep["svc_errors"] = jnp.asarray(errors, jnp.float32)
    return tl._replace(**rep)


def _spec(W, dt):
    return timeline_mod.TimelineSpec(
        num_windows=W, window_s=dt, num_services=2,
        hop_service=jnp.zeros(1, jnp.int32),
    )


def test_autoscaler_scales_up_at_sync_with_step_limit():
    _, t = _mini_tables("""
policies:
  worker:
    autoscaler: {min_replicas: 4, max_replicas: 16,
                 target_utilization: 50%, sync_period: 1s,
                 scale_up_step: 2}
""")
    dt = pol_mod.device_tables(t)
    spec = _spec(4, 1.0)
    # worker busy 3.6 s per 1 s window at 4 replicas -> util 0.9,
    # desired = ceil(4 * .9 / .5) = 8, step-limited to +2 per sync
    busy = np.zeros((2, 4))
    busy[1, :] = 3.6
    tl = _tl_with((4, 1.0), 2, busy=busy)
    state = pol_mod.init_state(dt)
    state, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, 4)), jnp.float32(4.0), spec
    )
    # 4 syncs, +2 each, bounded by desired recomputed per sync
    assert float(state.replicas[1]) > 4.0
    assert float(state.replicas[1]) <= 16.0
    assert float(state.scale_events[1]) >= 1


def test_autoscaler_stabilization_delays_scale_down():
    _, t = _mini_tables("""
policies:
  worker:
    autoscaler: {min_replicas: 1, max_replicas: 8,
                 target_utilization: 60%, sync_period: 1s,
                 stabilization_window: 3s, scale_down_step: 1}
""")
    dt = pol_mod.device_tables(t)
    # idle worker: desired = min_replicas
    tl = _tl_with((6, 1.0), 2, busy=np.zeros((2, 6)))
    spec = _spec(6, 1.0)
    state = pol_mod.init_state(dt)
    s2, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, 6)), jnp.float32(2.0), spec
    )
    # only 2 windows observed: stabilization (3 s below target) not met
    assert float(s2.replicas[1]) == 4.0
    s6, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, 6)), jnp.float32(6.0), spec
    )
    assert float(s6.replicas[1]) < 4.0


def test_autoscaler_uses_alive_capacity_under_chaos():
    """Review regression: utilization averages over ALIVE capacity.
    With 3 of 4 replicas chaos-downed and the single survivor
    saturated, the controller must scale UP — dividing by the actuated
    count would read util ~0.25 and scale the killed service DOWN."""
    _, t = _mini_tables("""
policies:
  worker:
    autoscaler: {min_replicas: 1, max_replicas: 16,
                 target_utilization: 50%, sync_period: 1s,
                 stabilization_window: 2s, scale_up_step: 2}
""")
    dt = pol_mod.device_tables(t)
    W = 4
    spec = _spec(W, 1.0)
    busy = np.zeros((2, W))
    busy[1, :] = 1.0  # one alive server fully busy
    tl = _tl_with((W, 1.0), 2, busy=busy)
    downed = np.zeros((2, W), np.float32)
    downed[1, :] = 3.0
    state = pol_mod.init_state(dt)
    s, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, W)), jnp.float32(4.0), spec,
        downed_w=jnp.asarray(downed),
    )
    assert float(s.replicas[1]) > 4.0
    # without the down delta the same signals scale DOWN (the bug)
    s_bug, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, W)), jnp.float32(4.0), spec
    )
    assert float(s_bug.replicas[1]) < 4.0


def test_retry_budget_no_bang_bang():
    """Review regression: the allow law reconstructs unsuppressed
    demand (observed / current allow), so steady demand D > headroom H
    settles at allow = H/D instead of oscillating H/D <-> 1."""
    _, t = _mini_tables("""
policies:
  worker:
    retry_budget: {budget_percent: 10%, min_retries_concurrent: 0}
""")
    dt = pol_mod.device_tables(t)
    W = 4
    spec = _spec(W, 1.0)
    arr = np.zeros((2, W))
    arr[1, :] = 100.0  # headroom = 10 retries/window
    tl = timeline_mod.zeros_summary(
        timeline_mod.TimelineSpec(
            num_windows=W, window_s=1.0, num_services=2,
            hop_service=jnp.zeros(1, jnp.int32),
        )
    )._replace(svc_arrivals=jnp.asarray(arr, jnp.float32))
    state = pol_mod.init_state(dt)
    # window 0: raw demand 40 observed at allow=1 -> allow = 0.25
    retries = np.zeros((2, W), np.float32)
    retries[1, 0] = 40.0
    s1, _ = pol_mod.advance(
        state, dt, tl, jnp.asarray(retries), jnp.float32(1.0), spec
    )
    assert float(s1.retry_allow[1]) == pytest.approx(0.25, rel=1e-3)
    # window 1: the SUPPRESSED observation (40 * 0.25 = 10) divided
    # back by allow reconstructs demand 40 -> allow HOLDS at 0.25
    retries[1, 1] = 10.0
    s2, _ = pol_mod.advance(
        s1, dt, tl, jnp.asarray(retries), jnp.float32(2.0), spec
    )
    assert float(s2.retry_allow[1]) == pytest.approx(0.25, rel=1e-3)


def test_shed_errors_do_not_feed_ejection():
    """Review regression: a shedding breaker's fast 500s must not
    accumulate the outlier-ejection streak (shed -> eject -> less
    capacity -> more shed would spiral)."""
    _, t = _mini_tables("""
policies:
  worker:
    breaker: {max_pending: 2, consecutive_errors: 5, base_ejection: 5s}
""")
    dt = pol_mod.device_tables(t)
    W = 6
    spec = _spec(W, 1.0)
    inflight = np.zeros((2, W))
    inflight[1, :] = 8.0     # breaker opens at window 0, stays open
    errors = np.zeros((2, W))
    errors[1, 1:] = 50.0     # the shed 500s, once shedding is active
    tl = _tl_with((W, 1.0), 2, inflight=inflight, errors=errors)
    state = pol_mod.init_state(dt)
    s, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, W)), jnp.float32(6.0), spec
    )
    # errors during shedding hold the streak instead of accumulating,
    # so the open breaker never converts its own 500s into an ejection
    assert float(s.shed[1]) > 0.0
    assert float(s.ejections[1]) == 0.0


def test_to_doc_truncates_unprocessed_windows():
    _, t = _mini_tables("""
policies:
  worker:
    breaker: {max_pending: 1000}
""")
    g2, compiled2 = None, compile_graph(graph_with_policies())
    dt = pol_mod.device_tables(t)
    spec = _spec(6, 1.0)
    state = pol_mod.init_state(dt)
    acc = pol_mod.zeros_summary(spec, 2)
    tl = _tl_with((6, 1.0), 2)
    state, delta = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, 6)), jnp.float32(3.0), spec
    )
    acc = pol_mod.accumulate_summary(acc, delta)
    doc = pol_mod.to_doc(compiled2, acc, t)
    w = doc["services"]["worker"]
    # only the 3 completed windows appear; no trailing zero-filled
    # rows that would read as replicas=0 / budget-capped
    assert len(w["replicas"]) == 3
    assert all(a == 1.0 for a in w["retry_allow"])
    assert "budget-capped" not in pol_mod.format_table(doc)


def test_outlier_ejection_trips_and_restores():
    _, t = _mini_tables("""
policies:
  worker:
    breaker: {consecutive_errors: 10, base_ejection: 2s,
              max_ejection_fraction: 50%}
""")
    dt = pol_mod.device_tables(t)
    W = 8
    spec = _spec(W, 1.0)
    errors = np.zeros((2, W))
    errors[1, 0:2] = 6.0  # streak of erroring windows sums past 10
    tl = _tl_with((W, 1.0), 2, errors=errors)
    state = pol_mod.init_state(dt)
    s2, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, W)), jnp.float32(2.0), spec
    )
    assert float(s2.ejected[1]) == 1.0
    assert float(s2.ejections[1]) == 1.0
    fx = pol_mod.effects(s2)
    assert float(fx.replicas[1]) == 3.0  # 4 static - 1 ejected
    # the baseline interval expires -> capacity returns
    s_all, _ = pol_mod.advance(
        s2, dt, tl, jnp.zeros((2, W)), jnp.float32(float(W)), spec
    )
    assert float(s_all.ejected[1]) == 0.0


def test_breaker_opens_on_queue_overflow_and_closes():
    _, t = _mini_tables("""
policies:
  worker:
    breaker: {max_pending: 2}
""")
    dt = pol_mod.device_tables(t)
    W = 4
    spec = _spec(W, 1.0)
    inflight = np.zeros((2, W))
    inflight[1, 0] = 8.0  # queue depth 8 >> max_pending 2 in window 0
    tl = _tl_with((W, 1.0), 2, inflight=inflight)
    state = pol_mod.init_state(dt)
    s1, delta = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, W)), jnp.float32(1.0), spec
    )
    assert float(s1.shed[1]) == pytest.approx(0.75)  # 1 - 2/8
    assert float(s1.trips[1]) == 1.0
    s2, _ = pol_mod.advance(
        s1, dt, tl, jnp.zeros((2, W)), jnp.float32(2.0), spec
    )
    assert float(s2.shed[1]) == 0.0  # closes once the queue clears


def test_stuck_breaker_chaos_never_closes():
    _, t = _mini_tables("""
policies:
  worker:
    breaker: {max_pending: 2}
""")
    dt = pol_mod.device_tables(t)
    W = 4
    spec = _spec(W, 1.0)
    inflight = np.zeros((2, W))
    inflight[1, 0] = 8.0
    tl = _tl_with((W, 1.0), 2, inflight=inflight)
    state = pol_mod.init_state(dt)
    s, _ = pol_mod.advance(
        state, dt, tl, jnp.zeros((2, W)), jnp.float32(4.0), spec,
        stuck_breaker=True,
    )
    assert float(s.shed[1]) == pytest.approx(0.75)  # still open at w3


def test_autoscaler_lag_chaos_delays_first_sync():
    _, t = _mini_tables("""
policies:
  worker:
    autoscaler: {min_replicas: 1, max_replicas: 8, sync_period: 1s}
""")
    dt = pol_mod.device_tables(t)
    s0 = pol_mod.init_state(dt)
    s_lag = pol_mod.init_state(dt, lag_periods=2)
    assert float(s_lag.next_sync_s[1]) == pytest.approx(
        float(s0.next_sync_s[1]) + 2.0
    )


def test_fault_spec_policy_sites():
    plan = faults.FaultPlan.parse(
        "stuck:policies.stuck_breaker,lag:policies.autoscaler_lag:3"
    )
    assert plan.stuck_breaker()
    assert plan.autoscaler_lag() == 3
    assert "stuck" in plan.signature() and "lag" in plan.signature()
    with pytest.raises(ValueError, match="stuck faults target"):
        faults.FaultPlan.parse("stuck:engine.run")
    with pytest.raises(ValueError, match="lag faults target"):
        faults.FaultPlan.parse("lag:engine.run")


def test_transient_policy_site_is_retried():
    """The retry-path test: a transient at the policy chaos site is
    classified and retried by the supervisor, and the run succeeds on
    the second attempt."""
    from isotope_tpu.resilience import (
        ResiliencePolicy,
        call_with_retries,
    )
    from isotope_tpu.resilience.taxonomy import TRANSIENT, classify

    g = graph_with_policies()
    compiled = compile_graph(g)
    sim = Simulator(
        compiled, SimParams(timeline=True), policies=tables_for(g)
    )
    load = LoadModel(kind="open", qps=1_000.0)
    faults.install("transient:policies.stuck_breaker:1")
    try:
        with pytest.raises(Exception) as e:
            sim.run_policies(load, 512, KEY, block_size=256)
        assert classify(e.value) == TRANSIENT
        faults.install("transient:policies.autoscaler_lag:1")
        out = call_with_retries(
            lambda: sim.run_policies(load, 512, KEY, block_size=256),
            site="policies.run",
            policy=ResiliencePolicy(max_retries=2,
                                    sleep=lambda s: None),
        )
        assert float(out[0].count) >= 512
    finally:
        faults.clear()


# -- end-to-end: engine co-sim ---------------------------------------------


@pytest.fixture(scope="module")
def storm_case():
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    breaker: {max_pending: 6}
    retry_budget: {budget_percent: 20%, min_retries_concurrent: 2}
    autoscaler: {min_replicas: 4, max_replicas: 12,
                 target_utilization: 50%, sync_period: 1s,
                 stabilization_window: 10s, scale_up_step: 2}
""")
    compiled = compile_graph(g)
    return g, compiled, tables_for(g)


@pytest.mark.slow
def test_protected_run_beats_unprotected(storm_case):
    g, compiled, tables = storm_case
    params = SimParams(timeline=True, timeline_window_s=1.0)
    chaos = (ChaosEvent(service="worker", start_s=1.0, end_s=3.0,
                        replicas_down=3),)
    qps = 0.325 * 4 * MU
    load = LoadModel(kind="open", qps=qps)
    n, block = 84_000, 4_096
    prot = Simulator(compiled, params, chaos, policies=tables)
    s_p, tl_p, pol = prot.run_policies(
        load, n, KEY, block_size=block, window_s=1.0
    )
    unprot = Simulator(compiled, params, chaos)
    s_u, _ = unprot.run_timeline(
        load, n, KEY, block_size=block, window_s=1.0
    )
    assert float(s_p.hop_events) < float(s_u.hop_events)
    assert float(s_p.error_count) < float(s_u.error_count)
    doc = pol_mod.to_doc(compiled, pol, tables)
    w = doc["services"]["worker"]
    assert w["breaker_trip_onset_s"] is not None
    assert 1.0 <= w["breaker_trip_onset_s"] <= 3.0
    assert w["peak_replicas"] > 4
    # format_table renders without error
    assert "replicas" in pol_mod.format_table(doc)


@pytest.mark.slow
def test_closed_loop_policy_run(storm_case):
    """Paced closed-loop policy runs work; window completion is gated
    by the SLOWEST connection's clock (review regression: conn_end
    .max() would finalize windows later blocks still write into)."""
    g, compiled, tables = storm_case
    params = SimParams(timeline=True, timeline_window_s=0.5)
    sim = Simulator(compiled, params, policies=tables)
    load = LoadModel(kind="closed", qps=2_000.0, connections=8)
    s, tl, pol = sim.run_policies(
        load, 8_192, KEY, block_size=1_024, window_s=0.5
    )
    assert float(s.count) >= 8_192
    done = np.asarray(pol.windows_done)
    assert done.sum() >= 1
    # processed windows form a contiguous prefix
    k = int(done.sum())
    assert (done[:k] == 1).all() and (done[k:] == 0).all()


@pytest.mark.slow
@pytest.mark.slow
def test_attributed_policy_run(storm_case):
    """run_policies(attribution=True) reduces blame over the SAME
    protected blocks: counts reconcile, and the protected worker's
    timeout blame sits below the unprotected twin's."""
    g, compiled, tables = storm_case
    params = SimParams(
        timeline=True, timeline_window_s=1.0, attribution=True
    )
    chaos = (ChaosEvent(service="worker", start_s=1.0, end_s=3.0,
                        replicas_down=3),)
    load = LoadModel(kind="open", qps=0.325 * 4 * MU)
    n, block = 42_000, 4_096
    prot = Simulator(compiled, params, chaos, policies=tables)
    s_p, _, _, attr_p = prot.run_policies(
        load, n, KEY, block_size=block, window_s=1.0,
        attribution=True,
    )
    assert float(attr_p.count) == float(s_p.count)
    unprot = Simulator(compiled, params, chaos)
    _, attr_u = unprot.run_attributed(load, n, KEY, block_size=block)
    w = list(compiled.services.names).index("worker")
    w_hops = compiled.hop_service == w
    assert (
        float(np.asarray(attr_p.timeout_blame)[w_hops].sum())
        < float(np.asarray(attr_u.timeout_blame)[w_hops].sum())
    )
    # without SimParams.attribution the attributed variant refuses
    with pytest.raises(ValueError, match="attribution"):
        Simulator(
            compiled, SimParams(timeline=True), chaos,
            policies=tables,
        ).run_policies(load, 512, KEY, attribution=True)


def test_feedback_respects_retry_budget(storm_case):
    """The static visit fixed point under a chaos storm must estimate
    strictly lower amplification with the budget than without."""
    g, compiled, tables = storm_case
    chaos = (ChaosEvent(service="worker", start_s=0.0, end_s=1e9,
                        replicas_down=2),)
    qps = 0.325 * 4 * MU
    with_b = Simulator(
        compiled, SimParams(timeline=True), chaos, policies=tables
    )
    without = Simulator(compiled, SimParams(timeline=True), chaos)
    assert with_b._feedback is not None and with_b._feedback.budget
    v_b = with_b._feedback.visits_pc(qps)
    v_u = without._feedback.visits_pc(qps)
    w = list(compiled.services.names).index("worker")
    assert v_b[0, w] < v_u[0, w]


def test_feedback_budget_noop_at_quiet_load(storm_case):
    g, compiled, tables = storm_case
    sim = Simulator(compiled, SimParams(timeline=True), policies=tables)
    dyn = sim._feedback.visits_pc(0.01 * MU)
    static = np.asarray(sim._visits_pc, np.float64)
    np.testing.assert_allclose(dyn, static, rtol=0.02)


# -- sharded twin ----------------------------------------------------------


@pytest.mark.slow
@pytest.mark.slow
def test_sharded_policies_bit_equal_to_emulated_twin(storm_case):
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g, compiled, tables = storm_case
    params = SimParams(timeline=True, timeline_window_s=1.0)
    chaos = (ChaosEvent(service="worker", start_s=1.0, end_s=2.0,
                        replicas_down=3),)
    load = LoadModel(kind="open", qps=0.325 * 4 * MU)
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=4, svc=1)), params, chaos,
        policies=tables,
    )
    args = dict(block_size=2_048, window_s=1.0)
    s_dev, tl_dev, pol_dev = sh.run_policies(load, 40_000, KEY, **args)
    s_em, tl_em, pol_em = sh.run_policies_emulated(
        load, 40_000, KEY, **args
    )
    for a, b in (
        (tl_dev, tl_em), (pol_dev, pol_em), (s_dev, s_em),
    ):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert np.array_equal(np.asarray(x), np.asarray(y))


def test_sharded_policies_reject_svc_mesh(storm_case):
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g, compiled, tables = storm_case
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=4, svc=2)),
        SimParams(timeline=True), policies=tables,
    )
    with pytest.raises(ValueError, match="svc=1"):
        sh.run_policies(
            LoadModel(kind="open", qps=1_000.0), 1_024, KEY
        )


@pytest.mark.slow
@pytest.mark.slow
def test_emulated_mesh_policy_twin_runs(storm_case):
    """An EmulatedMesh (no devices) replays the policy program for any
    host count on one device."""
    from isotope_tpu.parallel import MeshSpec, ShardedSimulator
    from isotope_tpu.parallel.mesh import EmulatedMesh

    g, compiled, tables = storm_case
    sh = ShardedSimulator(
        compiled, EmulatedMesh(MeshSpec(data=2, svc=1, slices=2)),
        SimParams(timeline=True, timeline_window_s=1.0),
        policies=tables,
    )
    load = LoadModel(kind="open", qps=2_000.0)
    s, tl, pol = sh.run_policies_emulated(
        load, 8_192, KEY, block_size=1_024, window_s=1.0
    )
    assert float(s.count) >= 8_192
    assert float(np.asarray(tl.arrivals).sum()) == float(s.count)
    with pytest.raises(ValueError, match="device mesh"):
        sh.run_policies(load, 8_192, KEY)


# -- runner / vet ----------------------------------------------------------


def test_runner_policy_main_run(tmp_path, storm_case):
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )
    from isotope_tpu.runner.run import run_experiment

    g, _, _ = storm_case
    topo = tmp_path / "storm.yaml"
    topo.write_text(g.to_yaml())
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(2_000.0,),
        connections=(8,),
        duration_s=3.0,
        load_kind="open",
        num_requests=6_000,
        policies=True,
        timeline_window_s=1.0,
    )
    (res,) = run_experiment(config, out_dir=str(tmp_path / "out"))
    assert not res.failed
    assert res.policies is not None
    assert res.policies["schema"] == "isotope-policies/v1"
    assert res.timeline is not None
    assert res.flat.get("_policies") is True
    assert (tmp_path / "out" /
            f"{res.label}.policies.json").exists()


def test_vet_policy_rules():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    retry_budget: {budget_percent: 0, min_retries_concurrent: 0}
    autoscaler: {min_replicas: 6, max_replicas: 2, sync_period: 1s}
""")
    params = SimParams(timeline_window_s=10.0)
    ids = [f.rule for f in lint_graph(g, params=params)]
    assert "VET-T011" in ids  # min > max
    assert "VET-T012" in ids  # zero budget on a retried target
    assert "VET-T013" in ids  # sync faster than the recorder window

    # a block that does not decode at all is its own rule (VET-T014),
    # not conflated with the min>max clamp rule
    bad = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    breker: {max_pending: 1}
""")
    ids_bad = [f.rule for f in lint_graph(bad, params=params)]
    assert "VET-T014" in ids_bad and "VET-T011" not in ids_bad


def test_vet_breaker_capacity_rule(tmp_path):
    from isotope_tpu.analysis.topo_lint import lint_config
    from isotope_tpu.runner.config import (
        DEFAULT_ENVIRONMENTS,
        ExperimentConfig,
    )

    topo = tmp_path / "tight.yaml"
    topo.write_text(CHAIN + """
policies:
  worker:
    breaker: {max_pending: 0.001, max_connections: 0.001}
""")
    config = ExperimentConfig(
        topology_paths=(str(topo),),
        environments=(DEFAULT_ENVIRONMENTS["NONE"],),
        qps=(0.9 * 4 * MU,),
        connections=(8,),
        duration_s=10.0,
        load_kind="open",
    )
    findings, _ = lint_config(config)
    assert any(f.rule == "VET-T010" for f in findings)


def test_vet_clean_policies_no_findings():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = graph_with_policies()
    params = SimParams(timeline_window_s=1.0)
    ids = [
        f.rule for f in lint_graph(g, params=params)
        if f.rule.startswith("VET-T01")
    ]
    assert ids == []
