"""Trace export: parent/child containment, statuses, CLI round trip."""
import json

import jax
import numpy as np
import pytest
import yaml

from isotope_tpu import cli
from isotope_tpu.compiler import compile_graph
from isotope_tpu.metrics.trace import chrome_trace, jaeger_trace
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, Simulator

TOPO = """
services:
- name: entry
  isEntrypoint: true
  script:
  - sleep: 2ms
  - [{call: left}, {call: right}]
  - call: tail
- name: left
  script: [{call: leaf}]
- name: right
- name: tail
  errorRate: 30%
- name: leaf
"""


def run(n=24, seed=0):
    compiled = compile_graph(ServiceGraph.decode(yaml.safe_load(TOPO)))
    sim = Simulator(compiled)
    res = sim.run(
        LoadModel(kind="open", qps=200.0), n, jax.random.PRNGKey(seed)
    )
    return compiled, res


def test_chrome_trace_containment_and_status():
    compiled, res = run()
    doc = chrome_trace(compiled, res)
    events = doc["traceEvents"]
    assert events
    by_req = {}
    for e in events:
        by_req.setdefault(e["pid"], {})[e["args"]["hop"]] = e
    for spans in by_req.values():
        for e in spans.values():
            p = e["args"]["parent_hop"]
            if p < 0:
                continue
            parent = spans[p]
            # child executes inside its caller's span (wire time is
            # outside the child but inside the parent)
            assert e["ts"] >= parent["ts"] - 1e-6
            assert e["ts"] + e["dur"] <= parent["ts"] + parent["dur"] + 1e-6
    # the flaky 'tail' service produced some 500s across requests
    statuses = {
        e["args"]["status"] for e in events if e["name"] == "tail"
    }
    assert 500 in statuses and 200 in statuses
    # depth is the thread id
    assert {e["tid"] for e in events} == {0, 1, 2}


def test_chrome_trace_respects_max_requests():
    compiled, res = run()
    doc = chrome_trace(compiled, res, max_requests=5)
    assert {e["pid"] for e in doc["traceEvents"]} == set(range(5))


def test_jaeger_trace_references_resolve():
    compiled, res = run()
    doc = jaeger_trace(compiled, res, max_requests=8)
    assert len(doc["data"]) == 8
    for trace in doc["data"]:
        ids = {s["spanID"] for s in trace["spans"]}
        by_id = {s["spanID"]: s for s in trace["spans"]}
        roots = 0
        for s in trace["spans"]:
            assert s["traceID"] == trace["traceID"]
            assert s["processID"] in trace["processes"]
            if not s["references"]:
                roots += 1
                continue
            (ref,) = s["references"]
            assert ref["refType"] == "CHILD_OF"
            assert ref["spanID"] in ids
            parent = by_id[ref["spanID"]]
            assert s["startTime"] >= parent["startTime"]
            assert (
                s["startTime"] + s["duration"]
                <= parent["startTime"] + parent["duration"]
            )
        assert roots == 1  # exactly the entrypoint span


def test_unsent_hops_produce_no_spans():
    compiled, res = run()
    sent = np.asarray(res.hop_sent)
    doc = chrome_trace(compiled, res)
    assert len(doc["traceEvents"]) == int(sent.sum())


@pytest.mark.slow
def test_cli_trace_export(tmp_path, capsys):
    topo = tmp_path / "t.yaml"
    topo.write_text(TOPO)
    out = tmp_path / "trace.json"
    rc = cli.main(
        ["simulate", str(topo), "--qps", "100", "--duration", "30s",
         "--max-requests", "2000", "--flat",
         "--trace", str(out), "--trace-requests", "8"]
    )
    assert rc == 0
    doc = json.loads(out.read_text())
    assert {e["pid"] for e in doc["traceEvents"]} == set(range(8))
    assert "traced 8 requests" in capsys.readouterr().err

    out2 = tmp_path / "trace_jaeger.json"
    rc = cli.main(
        ["simulate", str(topo), "--qps", "100", "--duration", "30s",
         "--max-requests", "2000", "--flat",
         "--trace", str(out2), "--trace-format", "jaeger",
         "--trace-requests", "4"]
    )
    assert rc == 0
    doc = json.loads(out2.read_text())
    assert len(doc["data"]) == 4


def test_cli_trace_honors_entry_override(tmp_path):
    # the --trace re-run must compile with the SAME entrypoint as the
    # main run, or a multi-entry topology silently traces the wrong tree
    topo = tmp_path / "multi.yaml"
    topo.write_text(
        """
services:
- name: e1
  isEntrypoint: true
  script: [{call: leaf}]
- name: leaf
- name: e2
  isEntrypoint: true
"""
    )
    out = tmp_path / "trace.json"
    rc = cli.main(
        ["simulate", str(topo), "--qps", "50", "--duration", "10s",
         "--max-requests", "500", "--flat", "--entry", "e2",
         "--trace", str(out), "--trace-requests", "3"]
    )
    assert rc == 0
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert any("e2" in n for n in names)
    assert not any("e1" in n or "leaf" in n for n in names)
