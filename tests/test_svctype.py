"""ServiceType tests (mirrors svctype/service_type_test.go)."""
import pytest

from isotope_tpu.models.svctype import (
    InvalidServiceTypeStringError,
    ServiceType,
)


@pytest.mark.parametrize(
    "s,t", [("http", ServiceType.HTTP), ("grpc", ServiceType.GRPC)]
)
def test_from_string(s, t):
    assert ServiceType.from_string(s) == t


@pytest.mark.parametrize("s", ["", "HTTP", "tcp", "h2"])
def test_from_string_invalid(s):
    with pytest.raises(InvalidServiceTypeStringError):
        ServiceType.from_string(s)


def test_str():
    assert str(ServiceType.HTTP) == "HTTP"
    assert str(ServiceType.GRPC) == "gRPC"
    assert str(ServiceType.UNKNOWN) == ""


def test_encode():
    assert ServiceType.HTTP.encode() == "http"
    assert ServiceType.GRPC.encode() == "grpc"
