"""Graph compiler tests: IR -> CompiledGraph lowering.

The canonical 4-service graph (same shape as the reference's
isotope/example-topologies/canonical.yaml) exercises sequential steps,
concurrent fan-out, and shared sub-trees; cycle/budget/entrypoint errors
cover the compile-time guards.
"""
import numpy as np
import pytest

from isotope_tpu.compiler import (
    CycleError,
    HopBudgetExceededError,
    NoEntrypointError,
    compile_graph,
)
from isotope_tpu.models.graph import ServiceGraph

CANONICAL = """
defaults:
  requestSize: 1 KB
  responseSize: 1 KB
services:
- name: a
- name: b
- name: c
  script:
  - call: a
  - call: b
- name: d
  isEntrypoint: true
  script:
  - - call: a
    - call: c
  - call: b
"""


@pytest.fixture()
def canonical():
    return compile_graph(ServiceGraph.from_yaml(CANONICAL))


def test_canonical_unroll_shape(canonical):
    # d -> {a, c} -> c calls {a, b}; d then calls b.
    # Hops: d, [a, c, b], [a, b]  => 6 hops, depth 3.
    assert canonical.num_hops == 6
    assert canonical.depth == 3
    assert canonical.entry_service == canonical.services.index_of("d")
    names = canonical.services.names
    assert [names[s] for s in canonical.hop_service] == [
        "d", "a", "c", "b", "a", "b",
    ]
    assert list(canonical.hop_parent) == [-1, 0, 0, 0, 2, 2]
    assert list(canonical.hop_depth) == [0, 1, 1, 1, 2, 2]
    # d's concurrent group is step 0; its call to b is step 1.
    assert list(canonical.hop_step) == [-1, 0, 0, 1, 0, 1]


def test_canonical_levels_align_with_children(canonical):
    for d, level in enumerate(canonical.levels[:-1]):
        nxt = canonical.levels[d + 1]
        np.testing.assert_array_equal(level.child_ids, nxt.hop_ids)
        # every child's segment points into a real step slot of its parent
        assert (level.child_seg < level.num_hops * canonical.max_steps).all()
    assert canonical.levels[-1].num_children == 0


def test_request_sizes_from_defaults(canonical):
    # every call inherits the 1 KB (=1024 B) default requestSize
    assert (canonical.hop_request_size[1:] == 1024.0).all()
    assert canonical.hop_request_size[0] == 0.0


def test_expected_visits_deterministic(canonical):
    # All send probs are 1 and no errorRate: every hop always happens.
    visits = canonical.expected_visits()
    names = canonical.services.names
    got = {names[i]: v for i, v in enumerate(visits)}
    assert got == {"a": 2.0, "b": 2.0, "c": 1.0, "d": 1.0}


def test_reach_composes_probability_and_error_rate():
    g = ServiceGraph.from_yaml(
        """
services:
- name: entry
  isEntrypoint: true
  errorRate: 10%
  script:
  - call: {service: mid, probability: 50}
- name: mid
  script:
  - call: leaf
- name: leaf
"""
    )
    c = compile_graph(g)
    reach = {c.services.names[c.hop_service[i]]: c.hop_reach[i]
             for i in range(c.num_hops)}
    assert reach["entry"] == 1.0
    # mid is reached iff entry doesn't error (0.9) and the coin passes (0.5)
    assert reach["mid"] == pytest.approx(0.45)
    assert reach["leaf"] == pytest.approx(0.45)


def test_sleep_steps_lowered_to_base_durations():
    g = ServiceGraph.from_yaml(
        """
services:
- name: entry
  isEntrypoint: true
  script:
  - sleep: 10ms
  - - sleep: 5ms
    - sleep: 7ms
    - call: leaf
- name: leaf
"""
    )
    c = compile_graph(g)
    root = c.levels[0]
    assert root.step_is_real[0, :2].all()
    # step 0: plain sleep; step 1: concurrent group keeps max(5ms, 7ms)
    np.testing.assert_allclose(root.step_base[0, :2], [0.010, 0.007])
    # the group's call is a child anchored at step 1
    assert list(c.hop_step) == [-1, 1]


def test_cycle_rejected():
    g = ServiceGraph.from_yaml(
        """
services:
- name: a
  isEntrypoint: true
  script:
  - call: b
- name: b
  script:
  - call: a
"""
    )
    with pytest.raises(CycleError) as err:
        compile_graph(g)
    assert err.value.path == ["a", "b", "a"]


def test_hop_budget_guard():
    # a binary tree of depth 6 has 127 hops; budget of 50 must trip
    services = [
        {
            "name": f"s{d}",
            "script": [[{"call": f"s{d+1}"}, {"call": f"s{d+1}"}]],
        }
        for d in range(6)
    ] + [{"name": "s6"}]
    services[0]["isEntrypoint"] = True
    g = ServiceGraph.decode({"services": services})
    with pytest.raises(HopBudgetExceededError):
        compile_graph(g, max_hops=50)


def test_no_entrypoint_and_explicit_entry():
    g = ServiceGraph.from_yaml("services:\n- name: a\n- name: b\n")
    with pytest.raises(NoEntrypointError):
        compile_graph(g)
    c = compile_graph(g, entry="b")
    assert c.entry_service == 1
    with pytest.raises(ValueError):
        compile_graph(g, entry="nope")


def test_empty_graph_rejected():
    with pytest.raises(NoEntrypointError):
        compile_graph(ServiceGraph.decode({"services": []}))
