"""auto-mTLS switching: the time-phased per-edge tax overlay.

The reference's auto-mtls scale test alternately scales istio/legacy
deployments so the share of connections paying the mTLS handshake flips
over time (perf/load/auto-mtls/scale.py:1-130).  The simulation models
the data-plane consequence directly: ``MtlsSchedule`` cycles an extra
one-way per-edge latency by arrival time (sim/config.py).
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim import LoadModel, SimParams, Simulator
from isotope_tpu.sim.config import MtlsSchedule

KEY = jax.random.PRNGKey(5)
DET = SimParams(service_time="deterministic")

CHAIN3 = """
services:
- name: a
  isEntrypoint: true
  script: [{call: b}]
- name: b
  script: [{call: c}]
- name: c
"""


def test_mtls_schedule_validation():
    with pytest.raises(ValueError, match="period_s"):
        MtlsSchedule(period_s=0.0, taxes_s=(0.0,))
    with pytest.raises(ValueError, match="non-empty"):
        MtlsSchedule(period_s=1.0, taxes_s=())
    with pytest.raises(ValueError, match=">= 0"):
        MtlsSchedule(period_s=1.0, taxes_s=(-1e-3,))


def test_mtls_phase_latency_deltas():
    # deterministic service, quiet load: the alternating phases differ
    # by EXACTLY 2 legs x 3 edges x tax — the per-phase delta the
    # reference's alternation produces
    mtls = MtlsSchedule(period_s=5.0, taxes_s=(0.0, 1e-3))
    sim = Simulator(
        compile_graph(ServiceGraph.from_yaml(CHAIN3)), DET, mtls=mtls
    )
    load = LoadModel(kind="open", qps=10.0)
    res = sim.run(load, 200, KEY)
    st = np.asarray(res.client_start)
    lat = np.asarray(res.client_latency, np.float64)
    phase = (np.floor(st / 5.0).astype(int)) % 2
    lat_on = lat[phase == 1]
    lat_off = lat[phase == 0]
    assert len(lat_on) > 20 and len(lat_off) > 20
    delta = lat_on.mean() - lat_off.mean()
    assert delta == pytest.approx(2 * 3 * 1e-3, rel=1e-4)
    # within a phase the latency is constant (deterministic)
    assert lat_on.std() < 1e-9 and lat_off.std() < 1e-9


def test_mtls_fractional_mixed_fleet_phase():
    # a mixed istio/legacy fleet = fractional expected tax.  Phase
    # MEDIANS, not means: the service time is deterministic but the
    # M/M/k queueing wait is not — at this utilization almost every
    # wait draw is exactly 0, yet one rare nonzero draw in a phase of
    # ~80 requests shifts that phase's mean by ~1e-6 s, past a 1e-4
    # relative gate on ~5 ms latencies.  The median is immune to the
    # outlier and pins the per-phase tax exactly.
    mtls = MtlsSchedule(period_s=2.0, taxes_s=(2e-4, 5e-4, 1e-3))
    sim = Simulator(
        compile_graph(ServiceGraph.from_yaml(CHAIN3)), DET, mtls=mtls
    )
    res = sim.run(LoadModel(kind="open", qps=20.0), 240, KEY)
    st = np.asarray(res.client_start)
    lat = np.asarray(res.client_latency, np.float64)
    phase = (np.floor(st / 2.0).astype(int)) % 3
    base = np.median(lat[phase == 0]) - 2 * 3 * 2e-4
    for i, tax in enumerate((2e-4, 5e-4, 1e-3)):
        assert np.median(lat[phase == i]) == pytest.approx(
            base + 2 * 3 * tax, rel=1e-4
        )


def test_mtls_toml_surface(tmp_path):
    from isotope_tpu.runner.config import load_toml
    from isotope_tpu.runner.run import run_experiment

    topo = tmp_path / "t.yaml"
    topo.write_text(CHAIN3)
    cfg = tmp_path / "c.toml"
    cfg.write_text(
        f"""
topology_paths = ["{topo}"]
environments = ["NONE"]

[client]
qps = [100]
num_concurrent_connections = [4]
duration = "20s"
load_kind = "open"

[sim]
num_requests = 2000
service_time = "deterministic"

[mtls]
period = "5s"
taxes = ["0ms", "1ms"]
"""
    )
    c = load_toml(cfg)
    assert c.mtls == MtlsSchedule(period_s=5.0, taxes_s=(0.0, 1e-3))
    (result,) = run_experiment(c, out_dir=str(tmp_path / "out"))
    # the alternation widens the latency spread: p99 - p50 spans the
    # 6 ms on/off delta
    flat = result.flat
    assert flat["p99"] - flat["p50"] >= 5000  # microseconds
