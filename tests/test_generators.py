"""Topology generator tests (tree + realistic)."""
import numpy as np
import pytest

from isotope_tpu.models.generators import (
    ARCHETYPES,
    barabasi_albert_edges,
    realistic_topology,
    tree_topology,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.models.script import ConcurrentCommand


def test_tree_counts():
    doc = tree_topology(num_levels=3, num_branches=3)
    g = ServiceGraph.decode(doc)
    assert len(g) == 1 + 3 + 9
    (entry,) = g.entrypoints()
    assert entry.name == "svc-0"


def test_tree_children_called_concurrently():
    # create_tree_topology.py:79-80: one step that is a list of calls.
    doc = tree_topology(num_levels=2, num_branches=3)
    g = ServiceGraph.decode(doc)
    (entry,) = g.entrypoints()
    assert len(entry.script) == 1
    assert isinstance(entry.script[0], ConcurrentCommand)
    assert len(entry.script[0]) == 3


def test_tree_naming_scheme():
    doc = tree_topology(num_levels=2, num_branches=2)
    names = {s["name"] for s in doc["services"]}
    assert names == {"svc-0", "svc-0-0", "svc-0-1"}


def test_tree_leaf_has_no_script():
    doc = tree_topology(num_levels=2, num_branches=2)
    leaves = [s for s in doc["services"] if s["name"] != "svc-0"]
    assert all("script" not in s for s in leaves)


def test_ba_edges_connected_tree():
    rng = np.random.default_rng(0)
    edges = barabasi_albert_edges(50, power=0.9, zero_appeal=3.25, rng=rng)
    assert edges.shape == (49, 2)
    # every node except 0 appears exactly once as a child, parent < child
    assert sorted(edges[:, 1]) == list(range(1, 50))
    assert (edges[:, 0] < edges[:, 1]).all()


@pytest.mark.parametrize("archetype", sorted(ARCHETYPES))
def test_realistic_valid_graph(archetype):
    doc = realistic_topology(num_services=30, archetype=archetype, seed=1)
    g = ServiceGraph.decode(doc)  # validates: no undefined callees
    assert len(g) == 30
    (entry,) = g.entrypoints()
    assert entry.name == "mock-0"


def test_realistic_sequential_calls():
    # create_realistic_topology.py:176-187: children called sequentially.
    doc = realistic_topology(num_services=20, archetype="star", seed=2)
    g = ServiceGraph.decode(doc)
    for svc in g.services:
        for cmd in svc.script:
            assert not isinstance(cmd, ConcurrentCommand)


def test_realistic_star_is_flat():
    # power=0.9, zero_appeal=0.01 concentrates attachment on the hub.
    doc = realistic_topology(num_services=50, archetype="star", seed=3)
    entry = doc["services"][0]
    assert len(entry.get("script", [])) > 10


def test_realistic_unknown_archetype():
    with pytest.raises(ValueError):
        realistic_topology(num_services=5, archetype="mesh")


def test_ba_zero_appeal_rejected():
    import numpy as np
    import pytest

    from isotope_tpu.models.generators import barabasi_albert_edges

    with pytest.raises(ValueError, match="zero_appeal"):
        barabasi_albert_edges(10, 0.9, 0.0, np.random.default_rng(0))


def test_ba_parent_child_invariant_many_seeds():
    import numpy as np

    from isotope_tpu.models.generators import barabasi_albert_edges

    for seed in range(10):
        e = barabasi_albert_edges(
            2000, 0.05, 0.01, np.random.default_rng(seed)
        )
        assert (e[:, 0] < e[:, 1]).all()


def test_replicate_topology_instances():
    import yaml as _yaml

    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.generators import (
        replicate_topology,
        tree_topology,
    )
    from isotope_tpu.models.graph import ServiceGraph

    doc = replicate_topology(tree_topology(num_levels=2, num_branches=2), 3)
    g = ServiceGraph.decode(doc)
    assert len(g.services) == 3 * 3
    # each instance keeps its own entrypoint
    eps = [s.name for s in g.entrypoints()]
    assert eps == ["ns0-svc-0", "ns1-svc-0", "ns2-svc-0"]
    # calls stay within the instance
    c1 = compile_graph(g, entry="ns1-svc-0")
    names = {c1.services.names[i] for i in set(c1.hop_service.tolist())}
    assert names == {"ns1-svc-0", "ns1-svc-0-0", "ns1-svc-0-1"}
    # round-trips as YAML
    assert ServiceGraph.from_yaml(_yaml.safe_dump(doc))


def test_replicate_identity_and_validation():
    import pytest as _pytest

    from isotope_tpu.models.generators import (
        replicate_topology,
        tree_topology,
    )

    doc = tree_topology(num_levels=2, num_branches=2)
    assert replicate_topology(doc, 1) is doc
    with _pytest.raises(ValueError):
        replicate_topology(doc, 0)


def test_replicate_materializes_defaults_script():
    from isotope_tpu.models.generators import replicate_topology
    from isotope_tpu.models.graph import ServiceGraph

    doc = {
        "defaults": {"script": [{"call": "leaf"}], "responseSize": 64},
        "services": [
            {"name": "root", "isEntrypoint": True},
            {"name": "leaf", "script": []},
        ],
    }
    out = replicate_topology(doc, 2)
    g = ServiceGraph.decode(out)  # would raise on un-prefixed targets
    assert "script" not in out["defaults"]
    by_name = {s.name: s for s in g.services}
    call = by_name["ns1-root"].script[0]
    assert call.service_name == "ns1-leaf"
    assert int(by_name["ns0-leaf"].response_size) == 64


def test_powerlaw_topology_decodes_connected_tree():
    from isotope_tpu.compiler import compile_graph
    from isotope_tpu.models.generators import powerlaw_topology
    from isotope_tpu.models.graph import ServiceGraph

    doc = powerlaw_topology(100, seed=0)
    g = ServiceGraph.decode(doc)
    assert len(g.services) == 100
    # a tree: exactly n-1 edges, every service reachable from pl-0
    compiled = compile_graph(g, entry="pl-0")
    reached = {compiled.services.names[i]
               for i in set(compiled.hop_service.tolist())}
    assert len(reached) == 100
    calls = sum(
        sum(1 for c in (s.get("script") or []) if "call" in c)
        for s in doc["services"]
    )
    assert calls == 99


def test_powerlaw_topology_heavy_tail():
    from isotope_tpu.models.generators import powerlaw_topology

    doc = powerlaw_topology(200, exponent=2.0, seed=1)
    degs = sorted(
        (sum(1 for c in (s.get("script") or []) if "call" in c)
         for s in doc["services"]),
        reverse=True,
    )
    # hub-dominated: the top service out-fans the median by a lot,
    # and most services are leaves (the Zipf shift makes 0 common)
    assert degs[0] >= 10
    assert degs[len(degs) // 2] == 0
    assert sum(1 for d in degs if d == 0) > len(degs) // 2


def test_powerlaw_topology_choice_lists_and_validation():
    import pytest as _pytest

    from isotope_tpu.models.generators import powerlaw_topology
    from isotope_tpu.models.graph import ServiceGraph

    doc = powerlaw_topology(
        40, seed=2,
        sleep_choices=["1ms", "4ms"],
        error_rate_choices=["0%", "2%"],
    )
    g = ServiceGraph.decode(doc)
    rates = {float(s.error_rate) for s in g.services}
    assert rates == {0.0, 0.02}
    sleeps = {c.seconds for s in g.services
              for c in s.script if type(c).__name__ == "SleepCommand"}
    assert sleeps <= {1e-3, 4e-3} and sleeps
    with _pytest.raises(ValueError):
        powerlaw_topology(0)
