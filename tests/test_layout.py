"""Mesh specs (parallel/mesh.py) and the Automap-style layout search
(parallel/layout.py + analysis/costmodel.comm_table)."""
import pytest

from isotope_tpu.analysis import costmodel
from isotope_tpu.parallel import (
    MeshSpec,
    build_mesh,
    mesh_spec_from_env,
    parse_mesh_spec,
)
from isotope_tpu.parallel import layout
from isotope_tpu.parallel.mesh import ENV_MESH


# -- spec parsing ----------------------------------------------------------


def test_parse_positional_two_axes():
    assert parse_mesh_spec("4x2") == MeshSpec(data=4, svc=2)


def test_parse_positional_three_axes():
    assert parse_mesh_spec("2x2x2") == MeshSpec(data=2, svc=2, slices=2)


def test_parse_named_any_order_any_subset():
    assert parse_mesh_spec("svc=2,data=4") == MeshSpec(data=4, svc=2)
    assert parse_mesh_spec("slice=2,data=2,svc=2") == MeshSpec(
        data=2, svc=2, slices=2
    )
    assert parse_mesh_spec("data=8") == MeshSpec(data=8)


def test_parse_auto():
    assert parse_mesh_spec("auto") == "auto"
    assert parse_mesh_spec(" AUTO ") == "auto"


def test_parse_unknown_axis_is_key_pathed():
    with pytest.raises(ValueError, match=r"mesh: unknown mesh axis"):
        parse_mesh_spec("foo=3")


def test_parse_bad_size_is_key_pathed():
    with pytest.raises(ValueError, match=r"mesh\.svc"):
        parse_mesh_spec("data=2,svc=x")
    with pytest.raises(ValueError, match=r"mesh\.data"):
        parse_mesh_spec("bogus")


def test_parse_duplicate_axis_rejected():
    with pytest.raises(ValueError, match=r"mesh\.data: axis given"):
        parse_mesh_spec("data=2,data=4")


def test_parse_too_many_dims_rejected():
    with pytest.raises(ValueError, match="bad mesh spec"):
        parse_mesh_spec("2x2x2x2")


def test_spec_validates_axis_sizes():
    with pytest.raises(ValueError, match=r"mesh\.svc"):
        MeshSpec(data=2, svc=0)


def test_spec_describe_round_trips():
    for spec in (MeshSpec(4, 2), MeshSpec(2, 2, 2), MeshSpec(8)):
        assert parse_mesh_spec(spec.describe()) == spec


def test_spec_axis_names_collapse_without_slices():
    assert MeshSpec(4, 2).axis_names == ("data", "svc")
    assert MeshSpec(2, 2, 2).axis_names == ("slice", "data", "svc")
    assert MeshSpec(2, 2, 2).size == 8


def test_env_spec(monkeypatch):
    monkeypatch.delenv(ENV_MESH, raising=False)
    assert mesh_spec_from_env() is None
    monkeypatch.setenv(ENV_MESH, "4x2")
    assert mesh_spec_from_env() == MeshSpec(data=4, svc=2)
    monkeypatch.setenv(ENV_MESH, "wat=1")
    with pytest.raises(ValueError, match=ENV_MESH):
        mesh_spec_from_env()


def test_build_mesh_device_count_key_pathed():
    # the 8-device virtual CPU mesh (conftest) cannot host 16 shards
    with pytest.raises(ValueError, match=r"mesh: .*needs 16 devices"):
        build_mesh(MeshSpec(data=8, svc=2))


def test_build_mesh_multislice_axis_order():
    mesh = build_mesh(MeshSpec(data=2, svc=2, slices=2))
    assert mesh.axis_names == ("slice", "data", "svc")  # DCN outermost


# -- comm table ------------------------------------------------------------


def test_comm_table_single_slice_has_no_dcn_row():
    rows = costmodel.comm_table(100, data=4, svc=2)
    assert [r["collective"] for r in rows] == [
        "psum_replicated", "psum_scatter_svc",
    ]
    assert all(r["link"] == "ici" for r in rows)


def test_comm_table_dcn_row_carries_scattered_tile():
    rows = costmodel.comm_table(1024, data=2, svc=2, slices=2)
    by = {r["collective"]: r for r in rows}
    assert by["psum_dcn"]["link"] == "dcn"
    # DCN crosses AFTER the svc scatter: its payload is the replicated
    # group plus a 1/svc tile, strictly less than the full per-service
    # state
    full = by["psum_replicated"]["bytes"] + by["psum_scatter_svc"]["bytes"]
    assert by["psum_dcn"]["bytes"] < full


def test_comm_table_dcn_slower_than_ici_for_same_bytes():
    ici = costmodel._collective_s(1e6, 2, "ici")
    dcn = costmodel._collective_s(1e6, 2, "dcn")
    assert dcn > ici
    assert costmodel._collective_s(1e6, 1, "dcn") == 0.0


def test_comm_table_num_merges_scales_time():
    one = costmodel.comm_table(64, data=4, svc=2, num_merges=1)
    ten = costmodel.comm_table(64, data=4, svc=2, num_merges=10)
    for a, b in zip(one, ten):
        assert b["time_s"] == pytest.approx(10 * a["time_s"])


# -- layout search ---------------------------------------------------------


def test_enumerate_respects_device_count():
    for spec in layout.enumerate_specs(8, 1024, max_slices=2):
        assert spec.size == 8


def test_enumerate_never_pads_only_svc_shards():
    # svc axis never wider than the service count (=> never wider than
    # the padded service count either: s_pad >= svc always)
    for spec in layout.enumerate_specs(8, 3):
        assert spec.svc <= 3
    assert all(s.svc == 1 for s in layout.enumerate_specs(8, 1))


def test_enumerate_slices_pinned_to_host_count():
    # hosts ARE slices: with 2 hosts every candidate carries exactly
    # 2 slices — a flat mesh spanning hosts would run ICI-priced
    # collectives across DCN, the one mispricing the search must
    # never offer
    with_slices = layout.enumerate_specs(8, 100, max_slices=2)
    assert {s.slices for s in with_slices} == {2}
    # a host count that does not divide the devices cannot factor
    with pytest.raises(ValueError, match="divide"):
        layout.enumerate_specs(8, 100, max_slices=3)


def test_choose_respects_padded_service_width():
    best = layout.choose_layout(8, 1024)
    s_pad = -(-1024 // best.spec.svc) * best.spec.svc
    assert best.spec.svc <= s_pad
    assert best.spec.size == 8


def test_choose_beats_hardcoded_multichip_mesh():
    """ISSUE acceptance: --mesh auto scores <= the hand-picked
    {'slice': 2, 'data': 2, 'svc': 2} on the multichip dryrun shape
    (1024 services, 8 devices)."""
    auto = layout.choose_layout(8, 1024, max_slices=2)
    hand = layout.score_layout(MeshSpec(data=2, svc=2, slices=2), 1024)
    assert auto.score_s <= hand.score_s


def test_choose_slices_match_host_count():
    # single host: no DCN axis, ever
    assert layout.choose_layout(8, 1024, max_slices=1).spec.slices == 1
    # two hosts: the slice axis is mandatory (one slice per host)
    assert layout.choose_layout(8, 1024, max_slices=2).spec.slices == 2


def test_choose_tiny_service_count_narrow_svc():
    best = layout.choose_layout(8, 1)
    assert best.spec == MeshSpec(data=8, svc=1)


def test_choose_deterministic():
    a = layout.choose_layout(8, 200, max_slices=2)
    b = layout.choose_layout(8, 200, max_slices=2)
    assert a.spec == b.spec and a.score_s == b.score_s


def test_score_to_dict_shape():
    d = layout.choose_layout(4, 64).to_dict()
    assert set(d) == {"mesh", "score_s", "pad_fraction", "comm"}
    assert all({"collective", "link", "bytes", "time_s",
                "participants"} <= set(r) for r in d["comm"])
