"""Pluggable load-balancing laws (sim/lb.py): decode/tables, the
power-of-d wait law vs a host-side DES oracle, mixture laws, panic
routing, canary composition, byte-identity off, sharded twin
bit-equality, the scan-bucket protected-run pin (the lifted unrolled
restriction), the degraded-backend chaos site, and the VET rules."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.compiler import (
    compile_graph,
    compile_lb,
    compile_policies,
    compile_rollouts,
)
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.resilience import faults
from isotope_tpu.sim import lb as lb_mod
from isotope_tpu.sim import queueing
from isotope_tpu.sim.config import ChaosEvent, LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator

KEY = jax.random.PRNGKey(0)
MU = 13_000.0

CHAIN = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 8
  script:
  - call: worker
- name: worker
  numReplicas: 4
"""

LB_LR = """
policies:
  worker:
    lb: {policy: least_request, choices_d: 2}
"""


def graph_with_lb(extra: str = LB_LR) -> ServiceGraph:
    return ServiceGraph.from_yaml(CHAIN + extra)


def tables_for(graph: ServiceGraph):
    return compile_lb(graph, compile_graph(graph))


def _ulp_equal(a, b, maxulp=1):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        x, y = np.asarray(x), np.asarray(y)
        if np.issubdtype(x.dtype, np.floating):
            np.testing.assert_array_max_ulp(x, y, maxulp=maxulp)
        else:
            assert np.array_equal(x, y)


def _bit_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# -- decode / tables -------------------------------------------------------


def test_decode_defaults_shorthand_and_null():
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  defaults:
    lb: least_request
  worker:
    lb: {policy: ring_hash, hash_skew: 1.2}
""")
    lbs = lb_mod.LbSet.decode(g.policies, ["entry", "worker"])
    assert lbs.for_service("entry").policy == "least_request"
    assert lbs.for_service("entry").choices_d == 2  # default
    assert lbs.for_service("worker").policy == "ring_hash"
    assert lbs.for_service("worker").hash_skew == 1.2
    g2 = ServiceGraph.from_yaml(CHAIN + """
policies:
  defaults:
    lb: least_request
  worker:
    lb: null
""")
    lbs2 = lb_mod.LbSet.decode(g2.policies, ["entry", "worker"])
    assert lbs2.for_service("worker") is None
    assert lbs2.for_service("entry") is not None


def test_decode_rejects_bad_entries():
    with pytest.raises(ValueError, match="unknown lb fields"):
        lb_mod.LbPolicy.decode({"policy": "wrr", "spread": 2})
    with pytest.raises(ValueError, match="one of"):
        lb_mod.LbPolicy.decode("bogus")
    with pytest.raises(ValueError, match="choices_d only applies"):
        lb_mod.LbPolicy.decode({"policy": "ring_hash", "choices_d": 2})
    with pytest.raises(ValueError, match="hash_skew only applies"):
        lb_mod.LbPolicy.decode({"policy": "wrr", "hash_skew": 1.0})
    with pytest.raises(ValueError, match="weights only applies"):
        lb_mod.LbPolicy.decode(
            {"policy": "least_request", "weights": [1, 2]}
        )
    with pytest.raises(ValueError, match="positive"):
        lb_mod.LbPolicy.decode({"policy": "wrr", "weights": [1, 0]})
    with pytest.raises(ValueError, match="unknown service"):
        lb_mod.LbSet.decode({"ghost": {"lb": "fifo"}}, ["entry"])
    # key-pathed errors through the graph decode surface
    with pytest.raises(ValueError) as e:
        compile_lb(
            ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    lb: {policy: least_request, choices_d: 0}
"""),
            compile_graph(ServiceGraph.from_yaml(CHAIN)),
        )
    assert "policies.worker.lb" in str(e.value)


def test_build_tables_profile_and_signature():
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  entry:
    lb: {policy: ring_hash, hash_skew: 1.0}
  worker:
    lb: {policy: wrr, weights: [3, 1]}
""")
    t = tables_for(g)
    assert t is not None and t.any_mix and not t.any_lr
    assert "lb:" in t.signature()
    prof = t.backend_profile(4)
    e = list(t.names).index("entry")
    w = list(t.names).index("worker")
    # zipf ranks over the ring's arcs
    np.testing.assert_allclose(prof[e], [1, 1 / 2, 1 / 3, 1 / 4])
    # wrr weights cycle over pool growth
    np.testing.assert_allclose(prof[w], [3, 1, 3, 1])
    # round-trips through encode (raw block preserved)
    again = ServiceGraph.decode(g.encode())
    assert again.policies == g.policies


def test_compile_lb_none_without_entries():
    g = ServiceGraph.from_yaml(CHAIN)
    assert compile_lb(g, compile_graph(g)) is None
    # a policies block WITHOUT lb entries compiles policies, not lb
    g2 = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    breaker: {max_pending: 8}
""")
    c2 = compile_graph(g2)
    assert compile_lb(g2, c2) is None
    assert compile_policies(g2, c2) is not None


# -- wait laws -------------------------------------------------------------


def _law_params(extra, lam, k, mu=MU, k_max=None):
    g = graph_with_lb(extra)
    t = tables_for(g)
    k_max = k_max or int(np.max(k))
    dlb = lb_mod.device_tables(t, k_max)
    return t, lb_mod.wait_params(
        t, dlb, jnp.asarray(lam, jnp.float32),
        mu, jnp.asarray(k, jnp.int32), k_max,
    )


def test_d1_is_exact_mm1_random_dispatch():
    """choices_d=1 (uniform random per-backend dispatch) must be the
    EXACT M/M/1 law at every utilization: P(wait) = rho and the
    conditional rate mu(1 - rho) — the closed-form anchor of the
    truncated mean-field sum + geometric residue."""
    lam = np.array([[200.0, 0.95 * 4 * MU]])
    _, qp = _law_params(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 1}\n",
        lam, [[8, 4]],
    )
    rho = 0.95
    assert np.isclose(float(qp.p_wait[0, 1]), rho, rtol=1e-4)
    assert np.isclose(
        float(qp.wait_rate[0, 1]), MU * (1 - rho), rtol=1e-3
    )


def _des_jsq(lam, mu, k, d, n=120_000, seed=3):
    """Host-side DES oracle: JSQ(d) over k per-backend FCFS M/M/1
    queues (join the least-occupied of d sampled backends)."""
    from collections import deque

    rng = np.random.default_rng(seed)
    arr = rng.exponential(1.0 / lam, n).cumsum()
    svc = rng.exponential(1.0 / mu, n)
    ready = np.zeros(k)
    deps = [deque() for _ in range(k)]
    waits = np.empty(n)
    for i in range(n):
        t = arr[i]
        for s in range(k):
            dq = deps[s]
            while dq and dq[0] <= t:
                dq.popleft()
        cand = (
            rng.choice(k, size=d, replace=False)
            if d < k else np.arange(k)
        )
        s = cand[int(np.argmin([len(deps[c]) for c in cand]))]
        start = max(t, ready[s])
        ready[s] = start + svc[i]
        deps[s].append(ready[s])
        waits[i] = start - t
    w = waits[n // 5:]  # drop warmup
    return float((w > 1e-12).mean()), float(w.mean())


@pytest.mark.slow
def test_power_of_d_vs_des_oracle_two_backends():
    """The mean-field power-of-d law against a DES oracle on a
    2-backend station.  Stated envelope (lb.py docstring): the law is
    a k -> infinity asymptotic, a LOWER bound on the finite-k mean
    wait — P(wait) tracks the oracle within ~15%, the mean wait sits
    in [0.3, 1.05] x oracle, and the d-ordering (2 choices beat 1)
    matches the oracle's."""
    mu, k, rho = 1.0, 2, 0.8
    lam = rho * k * mu
    p_des, w_des = _des_jsq(lam, mu, k, d=2)
    p_des1, w_des1 = _des_jsq(lam, mu, k, d=1)
    t, qp = _law_params(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 2}\n",
        np.array([[0.1, lam]]), [[8, k]], mu=mu, k_max=8,
    )
    p_law = float(qp.p_wait[0, 1])
    w_law = p_law / float(qp.wait_rate[0, 1])
    assert abs(p_law - p_des) / p_des < 0.15
    assert 0.3 * w_des < w_law < 1.05 * w_des
    # the oracle confirms the law's direction: sampling 2 beats 1
    assert w_des < w_des1 and p_des < p_des1
    _, qp1 = _law_params(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 1}\n",
        np.array([[0.1, lam]]), [[8, k]], mu=mu, k_max=8,
    )
    w_law1 = float(qp1.p_wait[0, 1]) / float(qp1.wait_rate[0, 1])
    assert w_law < w_law1 and p_law < float(qp1.p_wait[0, 1])


def test_wrr_uniform_equals_random_dispatch_and_scale_invariance():
    """Determinism anchors of the wrr mixture: uniform weights are
    exactly uniform-random per-backend dispatch (the d=1 law), and
    weights are scale-free ([2,2] == [1,1])."""
    lam = np.array([[100.0, 0.8 * 4 * MU]])
    k = [[8, 4]]
    _, qp_u = _law_params(
        "policies:\n  worker:\n    lb: {policy: wrr}\n", lam, k
    )
    _, qp_1 = _law_params(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 1}\n", lam, k
    )
    np.testing.assert_allclose(
        np.asarray(qp_u.p_wait)[0, 1], np.asarray(qp_1.p_wait)[0, 1],
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(qp_u.wait_rate)[0, 1],
        np.asarray(qp_1.wait_rate)[0, 1], rtol=1e-4,
    )
    _, qp_2 = _law_params(
        "policies:\n  worker:\n"
        "    lb: {policy: wrr, weights: [2, 2, 2, 2]}\n", lam, k
    )
    _bit_equal(qp_u.p_wait, qp_2.p_wait)
    # and run-level determinism: no extra RNG stream — two runs of the
    # same key are identical
    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: wrr, weights: [3, 1, 1, 1]}\n"
    )
    c = compile_graph(g)
    sim = Simulator(c, lb=tables_for(g))
    load = LoadModel(kind="open", qps=2_000.0)
    _bit_equal(
        sim.run_summary(load, 1_024, KEY, block_size=512),
        sim.run_summary(load, 1_024, KEY, block_size=512),
    )


def test_mixture_hot_backend_flags_unstable():
    """A skewed ring saturates its hottest arc long before the
    aggregate does: rho_aggregate ~0.5 but the hot backend takes ~52%
    of the load -> per-backend rho > 1 -> unstable."""
    lam = np.array([[100.0, 0.5 * 4 * MU]])
    _, qp = _law_params(
        "policies:\n  worker:\n"
        "    lb: {policy: ring_hash, hash_skew: 2.0}\n",
        lam, [[8, 4]],
    )
    assert bool(qp.unstable[0, 1])
    assert float(qp.utilization[0, 1]) < 0.6  # aggregate still calm
    base = queueing.mmk_params(
        jnp.asarray(lam, jnp.float32), MU,
        jnp.asarray([[8, 4]], jnp.int32), 8,
    )
    assert not bool(base.unstable[0, 1])


def test_panic_split_flip_law():
    """The panic threshold is a flip: healthy fraction at/above it
    keeps the law untouched; below it the load scales by the fraction
    and the complement fast-fails."""
    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: fifo, panic_threshold: 50%}\n"
    )
    t = tables_for(g)
    dlb = lb_mod.device_tables(t, 4)
    lam = jnp.asarray([[100.0, 1000.0]])
    total = jnp.asarray([[8.0, 4.0]])
    for alive_w, expect_panic in ((2.0, False), (1.0, True)):
        alive = jnp.asarray([[8.0, alive_w]])
        lam_out, p_fail = lb_mod.panic_split(dlb, lam, alive, total)
        frac = alive_w / 4.0
        if expect_panic:
            assert np.isclose(float(lam_out[0, 1]), 1000.0 * frac)
            assert np.isclose(float(p_fail[0, 1]), 1.0 - frac)
        else:
            assert float(lam_out[0, 1]) == 1000.0
            assert float(p_fail[0, 1]) == 0.0
        # entry has no panic threshold: never panics
        assert float(p_fail[0, 0]) == 0.0


# -- byte-identity / neutrality pins ---------------------------------------


def test_lb_absent_byte_identical():
    """The acceptance pin: no ``lb:`` entries -> compile_lb is None ->
    a Simulator built with lb=None traces the same program as one
    never told about lb — run_summary outputs bit-equal leaf by
    leaf."""
    g = ServiceGraph.from_yaml(CHAIN)
    compiled = compile_graph(g)
    load = LoadModel(kind="open", qps=2_000.0)
    a = Simulator(compiled).run_summary(load, 2_048, KEY,
                                        block_size=512)
    b = Simulator(compiled, lb=compile_lb(g, compiled)).run_summary(
        load, 2_048, KEY, block_size=512
    )
    _bit_equal(a, b)


def test_fifo_tables_neutral_law_pin():
    """An all-fifo lb block with no panic is the neutral law: tables
    compile (and key the cache) but every wait draw stays on the
    legacy M/M/k path — <= 1 ULP against the no-tables run (exact
    today: the engine skips the selection entirely)."""
    g = graph_with_lb("policies:\n  worker:\n    lb: fifo\n")
    compiled = compile_graph(g)
    t = tables_for(g)
    assert t is not None and not t.active
    load = LoadModel(kind="open", qps=2_000.0)
    a = Simulator(compiled).run_summary(load, 2_048, KEY,
                                        block_size=512)
    b = Simulator(compiled, lb=t).run_summary(load, 2_048, KEY,
                                              block_size=512)
    _ulp_equal(a, b)


def test_active_law_changes_physics():
    """Sanity complement of the pins: an ACTIVE law must move the
    latency distribution (a skewed ring at rho 0.9 is not fifo)."""
    load = LoadModel(kind="open", qps=47_000.0)
    g0 = ServiceGraph.from_yaml(CHAIN)
    c0 = compile_graph(g0)
    a = Simulator(c0).run_summary(load, 4_096, KEY, block_size=1_024)
    g1 = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: ring_hash, hash_skew: 1.5}\n"
    )
    c1 = compile_graph(g1)
    b = Simulator(c1, lb=tables_for(g1)).run_summary(
        load, 4_096, KEY, block_size=1_024
    )
    assert float(b.latency_sum) > 2.0 * float(a.latency_sum)


def test_saturated_load_rejected_with_active_lb():
    g = graph_with_lb()
    compiled = compile_graph(g)
    sim = Simulator(compiled, lb=tables_for(g))
    sat = LoadModel(kind="closed", qps=None, connections=8)
    with pytest.raises(ValueError, match="-qps max"):
        sim.run_summary(sat, 256, KEY)


# -- panic routing end-to-end ----------------------------------------------


def test_panic_routing_keeps_tail_through_storm():
    """3 of 4 worker replicas die mid-run.  Without panic the lone
    survivor absorbs everything (rho >> 1); with panic_threshold 50%
    the dead-backend share fast-fails (worker hop 500s appear) and
    the survivor keeps its undegraded load — the client tail stays
    orders of magnitude lower."""
    chaos = (ChaosEvent(service="worker", start_s=0.05, end_s=10.0,
                        replicas_down=3),)
    load = LoadModel(kind="open", qps=30_000.0)
    g_p = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, panic_threshold: 50%}\n"
    )
    c_p = compile_graph(g_p)
    sim_p = Simulator(c_p, SimParams(timeline=True), chaos,
                      lb=tables_for(g_p))
    s_p, tl_p = sim_p.run_timeline(load, 8_192, KEY, block_size=2_048,
                                   window_s=0.05)
    g_0 = ServiceGraph.from_yaml(CHAIN)
    c_0 = compile_graph(g_0)
    s_0 = Simulator(c_0, chaos=chaos).run_summary(
        load, 8_192, KEY, block_size=2_048
    )
    assert float(s_p.latency_sum) < 0.2 * float(s_0.latency_sum)
    # the fast-fail share lands as worker-hop 500s in the recorder
    w = list(c_p.services.names).index("worker")
    err = np.asarray(tl_p.svc_errors, np.float64)[w]
    arr = np.asarray(tl_p.svc_arrivals, np.float64)[w]
    live = arr > 0
    share = err[live].sum() / arr[live].sum()
    assert 0.5 < share < 0.9  # ~0.75 of routed hops hit dead backends


def test_panic_composes_with_policy_ejection():
    """Protected-run composition: the panic inputs come from the
    policy state's actuated pool (total) and its ejection remainder
    (alive) — a forced PolicyFx with 3 of 4 ejected must panic a 50%
    threshold and scale the admitted wait-law load."""
    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, panic_threshold: 50%}\n"
        "    breaker: {consecutive_errors: 5, "
        "max_ejection_fraction: 0.9}\n"
    )
    compiled = compile_graph(g)
    pt = compile_policies(g, compiled)
    sim = Simulator(compiled, SimParams(timeline=True), policies=pt,
                    lb=tables_for(g))
    from isotope_tpu.sim import policies as pol_mod

    S = compiled.num_services
    w = list(compiled.services.names).index("worker")
    alive = np.full(S, 8.0)
    alive[w] = 1.0
    total = np.full(S, 8.0)
    total[w] = 4.0
    fx = pol_mod.PolicyFx(
        replicas=jnp.asarray(np.maximum(alive, 1.0), jnp.float32),
        shed=jnp.zeros(S, jnp.float32),
        retry_allow=jnp.ones(S, jnp.float32),
        total=jnp.asarray(total, jnp.float32),
        alive=jnp.asarray(alive, jnp.float32),
    )
    n = 2_048
    res, _, _ = sim._simulate_core(
        n, "open", 0, KEY, jnp.float32(20_000.0), jnp.float32(0.0),
        jnp.float32(20_000.0), jnp.float32(0.0), jnp.float32(0.0),
        jnp.zeros((1,), jnp.float32), jnp.float32(0.0),
        policy_fx=fx,
    )
    worker_cols = np.nonzero(
        np.asarray(compiled.hop_service) == w
    )[0]
    err = np.asarray(res.hop_error)[:, worker_cols]
    sent = np.asarray(res.hop_sent)[:, worker_cols]
    share = err.sum() / max(sent.sum(), 1)
    assert 0.6 < share < 0.9  # 1 - 1/4 healthy ~ 0.75 fast-fails


# -- canary composition ----------------------------------------------------


def test_ring_hash_composes_with_canary_split():
    """Hash stickiness respects version weights: each arm re-applies
    the ring over its OWN pool.  Unit law: a 1-replica canary arm's
    mixture collapses to M/M/1 of the canary lam regardless of skew;
    end-to-end: a rollout over a ring-hash service runs and its
    per-arm channel fills."""
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  worker:
    lb: {policy: ring_hash, hash_skew: 1.5}
rollouts:
  worker:
    steps: ["25%", "100%"]
    bake: 500ms
    gates: {min_samples: 10}
    canary: {replicas: 1}
""")
    compiled = compile_graph(g)
    t = tables_for(g)
    dlb = lb_mod.device_tables(t, 8)
    w = list(compiled.services.names).index("worker")
    # canary pool of 1: share vector is a point mass -> exact M/M/1
    lam = np.zeros((1, compiled.num_services), np.float32)
    lam[0, w] = 0.25 * 0.7 * MU
    k1 = np.ones((1, compiled.num_services), np.int32)
    qp = lb_mod.wait_params(t, dlb, jnp.asarray(lam), MU,
                            jnp.asarray(k1), 8)
    rho = float(lam[0, w]) / MU
    assert np.isclose(float(qp.p_wait[0, w]), rho, rtol=1e-4)
    assert np.isclose(float(qp.wait_rate[0, w]), MU * (1 - rho),
                      rtol=1e-3)
    rt = compile_rollouts(g, compiled)
    sim = Simulator(compiled, SimParams(timeline=True), rollouts=rt,
                    lb=t)
    out = sim.run_rollouts(
        LoadModel(kind="open", qps=10_000.0), 8_192, KEY,
        block_size=2_048, window_s=0.25,
    )
    roll = out[2]
    done = np.asarray(roll.windows_done) > 0
    assert done.any()
    # both arms actually served hops under the ring-hash law
    arr = np.asarray(roll.ver_arrivals, np.float64)
    assert arr[w, 0].sum() > 0 and arr[w, 1].sum() > 0


# -- sharded twin ----------------------------------------------------------


def test_sharded_lb_bit_equal_to_emulated_twin():
    from isotope_tpu.parallel import (
        MeshSpec,
        ShardedSimulator,
        build_mesh,
    )

    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: ring_hash, hash_skew: 1.2, "
        "panic_threshold: 40%}\n"
    )
    compiled = compile_graph(g)
    chaos = (ChaosEvent(service="worker", start_s=0.2, end_s=1.0,
                        replicas_down=3),)
    params = SimParams(timeline=True, timeline_window_s=0.25)
    sh = ShardedSimulator(
        compiled, build_mesh(MeshSpec(data=4, svc=1)), params, chaos,
        lb=tables_for(g),
    )
    load = LoadModel(kind="open", qps=20_000.0)
    out_dev = sh.run_timeline(load, 8_192, KEY, block_size=2_048,
                              window_s=0.25)
    out_em = sh.run_timeline_emulated(load, 8_192, KEY,
                                      block_size=2_048, window_s=0.25)
    _bit_equal(out_dev, out_em)


# -- the lifted scan-bucket restriction ------------------------------------


def _retry_chain(n=6, retries=1, timeout="600us"):
    out = ["services:"]
    names = ["entry"] + [f"s{i}" for i in range(1, n)]
    for i, nm in enumerate(names):
        out.append(f"- name: {nm}")
        if i == 0:
            out.append("  isEntrypoint: true")
        out.append("  numReplicas: 4")
        if i + 1 < n:
            out.append("  script:")
            out.append(
                f"  - call: {{service: {names[i + 1]}, "
                f"timeout: {timeout}, retries: {retries}}}"
            )
    return "\n".join(out) + """
policies:
  defaults:
    retry_budget: {budget_percent: 5%, min_retries_concurrent: 0}
  s3:
    lb: {policy: least_request, choices_d: 2}
"""


def test_policies_simulator_keeps_bucketed_plan():
    """The lifted restriction: a Simulator CARRYING policy tables now
    plans scan buckets like any other (previously it forced the
    unrolled trace)."""
    from isotope_tpu.compiler.buckets import ScanBucketPlan

    g = ServiceGraph.from_yaml(_retry_chain())
    compiled = compile_graph(g)
    sim = Simulator(
        compiled,
        SimParams(timeline=True, level_bucket_waste=8.0),
        policies=compile_policies(g, compiled),
        lb=compile_lb(g, compiled),
    )
    assert any(isinstance(p, ScanBucketPlan) for p in sim._plan)


@pytest.mark.slow
@pytest.mark.slow
def test_protected_scan_bucket_pins_to_unrolled():
    """The acceptance pin: run_policies under the default bucketed
    plan vs the unrolled plan — <= 1 ULP on every leaf (same law,
    same budget gate, the scan body's ops in lockstep with the
    unrolled attempt loop)."""
    g = ServiceGraph.from_yaml(_retry_chain())
    compiled = compile_graph(g)
    pt = compile_policies(g, compiled)
    lt = compile_lb(g, compiled)
    load = LoadModel(kind="open", qps=20_000.0)
    args = dict(block_size=1_024, window_s=0.1)
    pB = SimParams(timeline=True, timeline_window_s=0.1,
                   level_bucket_waste=8.0)
    pU = SimParams(timeline=True, timeline_window_s=0.1,
                   bucketed_scan=False)
    from isotope_tpu.compiler.buckets import ScanBucketPlan

    simB = Simulator(compiled, pB, policies=pt, lb=lt)
    assert any(isinstance(p, ScanBucketPlan) for p in simB._plan)
    simU = Simulator(compiled, pU, policies=pt, lb=lt)
    outB = simB.run_policies(load, 2_048, KEY, **args)
    outU = simU.run_policies(load, 2_048, KEY, **args)
    _ulp_equal(outB, outU)


@pytest.mark.slow
def test_protected_scan_bucket_storm_eager_bit_identical():
    """Under a chaos storm the budget gate ACTUATES inside the scan
    buckets; eagerly (no XLA fusion) the bucketed and unrolled
    protected runs are bit-identical — the levelscan equivalence
    contract extended to the budget gate.  (Under jit the closed
    control loop amplifies FMA-contraction rounding across blocks, so
    the jit pin lives in the no-storm test above.)"""
    g = ServiceGraph.from_yaml(_retry_chain())
    compiled = compile_graph(g)
    pt = compile_policies(g, compiled)
    chaos = (ChaosEvent(service="s4", start_s=0.1, end_s=0.4,
                        replicas_down=3),)
    load = LoadModel(kind="open", qps=40_000.0)
    args = dict(block_size=2_048, window_s=0.1)
    simB = Simulator(
        compiled,
        SimParams(timeline=True, timeline_window_s=0.1,
                  level_bucket_waste=8.0),
        chaos, policies=pt,
    )
    simU = Simulator(
        compiled,
        SimParams(timeline=True, timeline_window_s=0.1,
                  bucketed_scan=False),
        chaos, policies=pt,
    )
    with jax.disable_jit():
        outB = simB.run_policies(load, 8_192, KEY, **args)
        outU = simU.run_policies(load, 8_192, KEY, **args)
    _bit_equal(outB, outU)
    # and the budget visibly actuated (the gate is not dead code)
    ra = np.asarray(outB[2].retry_allow)
    done = np.asarray(outB[2].windows_done) > 0
    assert done.any() and float(ra[:, done].min()) < 1.0


# -- degraded-backend chaos site -------------------------------------------


def test_degraded_backend_chaos_site():
    """The gray-failure site: one backend's weight collapses in the
    traced profile — the wrr pool's survivors absorb its share (the
    physics shift is visible), the spec participates in the
    trace-affecting fault signature, and the standard kinds raise
    classified faults at the run entry (supervisor retry path, pinned
    like the PR 9 policy sites)."""
    from isotope_tpu.resilience import (
        ResiliencePolicy,
        call_with_retries,
    )
    from isotope_tpu.resilience.taxonomy import TRANSIENT, classify

    plan = faults.FaultPlan.parse("degrade:lb.degraded_backend:1")
    assert plan.lb_degraded_backend() == (1, plan.DEGRADED_FACTOR)
    assert "degrade:lb.degraded_backend:1" in plan.signature()
    with pytest.raises(ValueError, match="degrade faults target"):
        faults.FaultPlan.parse("degrade:engine.run")

    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: wrr, weights: [1, 1, 1, 1]}\n"
    )
    compiled = compile_graph(g)
    load = LoadModel(kind="open", qps=45_000.0)
    try:
        faults.clear()
        clean = Simulator(compiled, lb=tables_for(g)).run_summary(
            load, 4_096, KEY, block_size=1_024
        )
        faults.install("degrade:lb.degraded_backend:0")
        degraded = Simulator(compiled, lb=tables_for(g)).run_summary(
            load, 4_096, KEY, block_size=1_024
        )
        # a collapsed backend concentrates its share on 3 survivors:
        # rho_b 0.87 -> ~1.16 saturates them; waits explode
        assert float(degraded.latency_sum) > 1.5 * float(
            clean.latency_sum
        )
        # classified-fault entry + supervisor retry
        faults.install("transient:lb.degraded_backend:1")
        sim = Simulator(compiled, lb=tables_for(g))
        with pytest.raises(Exception) as e:
            sim.run_summary(load, 512, KEY, block_size=256)
        assert classify(e.value) == TRANSIENT
        faults.install("transient:lb.degraded_backend:1")
        out = call_with_retries(
            lambda: sim.run_summary(load, 512, KEY, block_size=256),
            site="lb.run",
            policy=ResiliencePolicy(max_retries=2,
                                    sleep=lambda s: None),
        )
        assert float(out.count) >= 512
    finally:
        faults.clear()


# -- feedback mirror -------------------------------------------------------


def test_feedback_mirrors_lb_wait_law():
    """The visit fixed point integrates the LB wait law through the
    numpy mirror: np_wait_stats agrees with the traced device law,
    the mirror's skewed mean wait exceeds the aggregate M/M/k's at
    the same load, and a Simulator with lb tables solves a DIFFERENT
    fixed point than the fifo twin."""
    # mirror == device law (per service, both laws)
    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  entry:
    lb: {policy: least_request, choices_d: 3}
  worker:
    lb: {policy: ring_hash, hash_skew: 2.0}
""")
    t = tables_for(g)
    prof = t.backend_profile(8)
    lam = np.array([0.6 * 8 * MU, 0.5 * 4 * MU])
    k = np.array([8.0, 4.0])
    p_np, r_np = lb_mod.np_wait_stats(t, prof, lam, MU, k)
    dlb = lb_mod.device_tables(t, 8)
    qp = lb_mod.wait_params(
        t, dlb, jnp.asarray(lam[None, :], jnp.float32), MU,
        jnp.asarray(k[None, :], jnp.int32), 8,
    )
    np.testing.assert_allclose(p_np, np.asarray(qp.p_wait)[0],
                               rtol=1e-4)
    np.testing.assert_allclose(r_np, np.asarray(qp.wait_rate)[0],
                               rtol=1e-3)
    # the skewed mirror sees the hot arc the aggregate law misses
    from isotope_tpu.sim.feedback import np_mmk

    p_f, r_f, _ = np_mmk(lam, MU, k)
    assert p_np[1] / r_np[1] > 2.0 * (p_f[1] / r_f[1])
    # and the engine's fixed point actually consumes the mirror
    topo = """
services:
- name: entry
  isEntrypoint: true
  numReplicas: 8
  script:
  - call: {service: worker, timeout: 2ms, retries: 2}
- name: worker
  numReplicas: 4
"""
    g0 = ServiceGraph.from_yaml(topo)
    sim0 = Simulator(compile_graph(g0))
    g1 = ServiceGraph.from_yaml(topo + """
policies:
  worker:
    lb: {policy: ring_hash, hash_skew: 2.0}
""")
    c1 = compile_graph(g1)
    sim1 = Simulator(c1, lb=compile_lb(g1, c1))
    assert sim0._feedback is not None
    assert sim1._feedback is not None and sim1._feedback.lb is not None
    qps = 0.3 * 4 * MU
    v0 = sim0._feedback.visits_pc(qps)
    v1 = sim1._feedback.visits_pc(qps)
    assert not np.allclose(v0, v1)


# -- artifacts / reporting -------------------------------------------------


def test_to_doc_and_format_table():
    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: wrr, weights: [3, 1, 1, 1], "
        "panic_threshold: 25%}\n"
    )
    compiled = compile_graph(g)
    t = tables_for(g)
    sim = Simulator(compiled, SimParams(timeline=True), lb=t)
    _, tl = sim.run_timeline(
        LoadModel(kind="open", qps=5_000.0), 4_096, KEY,
        block_size=1_024, window_s=0.2,
    )
    doc = lb_mod.to_doc(t, tl=tl)
    assert doc["schema"] == "isotope-lb/v1"
    svc = doc["services"]["worker"]
    assert svc["policy"] == "wrr"
    assert svc["panic_threshold"] == 0.25
    np.testing.assert_allclose(
        svc["share"], [0.5, 1 / 6, 1 / 6, 1 / 6], atol=1e-6
    )
    assert svc["window_split"] and all(
        len(row) == 4 for row in svc["window_split"]
    )
    # split reconciles with the recorder's arrivals
    w = list(compiled.services.names).index("worker")
    arr = np.asarray(tl.svc_arrivals, np.float64)[w]
    total_split = sum(sum(r) for r in svc["window_split"])
    assert np.isclose(
        total_split, arr[: len(svc["window_split"])].sum(), rtol=1e-3
    )
    text = lb_mod.format_table(doc)
    assert "worker" in text and "wrr" in text and "panic<25%" in text
    # entry declares nothing: absent from the doc
    assert "entry" not in doc["services"]


def test_to_doc_truncates_to_completed_policy_windows():
    """Protected runs pass a PolicySummary: the split must stop at
    pol.windows_done — never-advanced windows are zero-filled on
    device and would read as a pool collapsed to one backend."""
    from isotope_tpu.sim import policies as pol_mod

    g = graph_with_lb(
        "policies:\n  worker:\n"
        "    lb: {policy: wrr, weights: [3, 1, 1, 1]}\n"
    )
    t = tables_for(g)
    S, W = 2, 4
    arr = np.zeros((S, W))
    arr[1] = [40.0, 40.0, 0.0, 0.0]

    class _Tl:
        svc_arrivals = arr

    eff = np.zeros((S, W))
    eff[:, 0] = [8.0, 4.0]  # only window 0 completed
    pol = pol_mod.PolicySummary(
        window_s=np.float32(0.5),
        replicas=eff, effective=eff, shed=np.zeros((S, W)),
        retry_allow=np.ones((S, W)), ejected=np.zeros((S, W)),
        breaker_open=np.zeros((S, W)),
        windows_done=np.array([1.0, 0.0, 0.0, 0.0]),
        trips=np.zeros(S), ejections=np.zeros(S),
        scale_events=np.zeros(S),
    )
    doc = lb_mod.to_doc(t, tl=_Tl(), pol=pol)
    split = doc["services"]["worker"]["window_split"]
    assert len(split) == 1 and len(split[0]) == 4


# -- vet rules -------------------------------------------------------------


def test_vet_lb_rules():
    from isotope_tpu.analysis.topo_lint import lint_graph

    def rules(extra):
        g = ServiceGraph.from_yaml(CHAIN + extra)
        return [
            (f.rule, f.severity)
            for f in lint_graph(g)
            if f.rule in ("VET-T019", "VET-T020", "VET-T021",
                          "VET-T022")
        ]

    assert rules(
        "policies:\n  worker:\n"
        "    lb: {policy: least_request, choices_d: 9}\n"
    ) == [("VET-T019", "warn")]
    one_replica = CHAIN.replace("numReplicas: 4", "numReplicas: 1")
    g1 = ServiceGraph.from_yaml(
        one_replica + "policies:\n  worker:\n    lb: ring_hash\n"
    )
    from isotope_tpu.analysis.topo_lint import lint_graph as lg

    assert [(f.rule, f.severity) for f in lg(g1)
            if f.rule == "VET-T020"] == [("VET-T020", "info")]
    assert rules(
        "policies:\n  worker:\n"
        "    lb: {policy: fifo, panic_threshold: 100%}\n"
    ) == [("VET-T021", "error")]
    assert rules(
        "policies:\n  worker:\n"
        "    lb: {policy: fifo, panic_threshold: 20%}\n"
        "    breaker: {consecutive_errors: 5, "
        "max_ejection_fraction: 50%}\n"
    ) == [("VET-T021", "warn")]
    assert rules(
        "policies:\n  worker:\n    lb: {policy: bogus}\n"
    ) == [("VET-T022", "error")]
    # clean entry: no lb findings
    assert rules(LB_LR) == []


def test_vet_clean_lb_no_findings():
    from isotope_tpu.analysis.topo_lint import lint_graph

    g = ServiceGraph.from_yaml(CHAIN + """
policies:
  defaults:
    lb: least_request
  worker:
    lb: {policy: wrr, weights: [2, 1, 1, 1], panic_threshold: 30%}
""")
    assert [f for f in lint_graph(g)
            if f.rule.startswith("VET-T0") and f.rule >= "VET-T019"] \
        == []
