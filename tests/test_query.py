"""Prometheus query layer: parser, evaluator, histogram_quantile.

Mirrors the consumer shapes of the reference's prom.py:92-126 (canned
CPU/mem aggregations) and :216-232 (histogram_quantile fetcher).
"""
import math

import pytest

from isotope_tpu.metrics.query import (
    MetricStore,
    QueryError,
    parse_exposition,
)

EXPO = """\
# HELP m_total A counter.
# TYPE m_total counter
m_total{service="a",code="200"} 90
m_total{service="a",code="500"} 10
m_total{service="b",code="200"} 50
gauge_bytes{service="a"} 1024
gauge_bytes{service="b"} 4096
h_bucket{service="a",le="0.1"} 20
h_bucket{service="a",le="0.5"} 80
h_bucket{service="a",le="+Inf"} 100
"""

STORE = MetricStore.from_text(EXPO, duration_s=10.0)


def test_parse_exposition():
    samples = parse_exposition(EXPO)
    assert len(samples) == 8
    assert samples[0].name == "m_total"
    assert samples[0].labels == {"service": "a", "code": "200"}
    assert samples[0].value == 90.0


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_exposition("not a metric line at all!")
    # malformed label pairs must raise, not silently drop
    with pytest.raises(ValueError):
        parse_exposition('m{a="x",b=nope} 3')


def test_instant_selector_and_matchers():
    assert STORE.query_value('m_total{service="a",code="200"}') == 90
    assert STORE.query_value('m_total{service="b"}') == 50
    # != and regex matchers (fully anchored, like Prometheus)
    assert STORE.query_value(
        'sum(m_total{code!="500"})'
    ) == 140
    assert STORE.query_value('sum(m_total{code=~"5.."})') == 10
    assert STORE.query_value('sum(m_total{code!~"5.."})') == 140
    # no match -> empty vector -> fetch_value semantics: 0
    assert STORE.query_value('m_total{service="nosuch"}') == 0.0


def test_rate_divides_by_run_duration():
    assert STORE.query_value(
        'rate(m_total{service="a",code="500"}[1m])'
    ) == pytest.approx(1.0)
    # the bracketed window is parsed but the run is the window
    assert STORE.query_value(
        'rate(m_total{service="a",code="500"}[5m])'
    ) == pytest.approx(1.0)


def test_sum_by_and_without():
    v = STORE.query('sum(m_total) by (service)')
    assert v[(("service", "a"),)] == 100
    assert v[(("service", "b"),)] == 50
    w = STORE.query('sum(m_total) without (code)')
    assert w == v
    assert STORE.query_value('max(sum(m_total) by (service))') == 100
    assert STORE.query_value('avg(sum(m_total) by (service))') == 75
    assert STORE.query_value('count(sum(m_total) by (service))') == 2


def test_scalar_arithmetic():
    assert STORE.query_value(
        'sum(rate(m_total[1m])) * 1000'
    ) == pytest.approx(15000.0)
    assert STORE.query_value(
        'max(gauge_bytes) * 9.5367431640625e-07'
    ) == pytest.approx(4096 / 2**20)


def test_max_over_time_identity():
    assert STORE.query_value(
        'max(max_over_time(gauge_bytes[1m]))'
    ) == 4096


def test_histogram_quantile_interpolates():
    # 20 <= 0.1, 80 <= 0.5, 100 total.  p50: rank 50 in (0.1, 0.5]:
    # 0.1 + 0.4 * (50-20)/(80-20) = 0.3
    got = STORE.query_value(
        'histogram_quantile(0.5, h_bucket{service="a"})'
    )
    assert got == pytest.approx(0.3)
    # p10 falls in the first bucket: interpolate from 0
    got = STORE.query_value(
        'histogram_quantile(0.1, h_bucket{service="a"})'
    )
    assert got == pytest.approx(0.1 * 10 / 20)
    # p99 beyond the last finite bucket: report the last finite bound
    got = STORE.query_value(
        'histogram_quantile(0.99, h_bucket{service="a"})'
    )
    assert got == pytest.approx(0.5)


def test_histogram_quantile_reference_shape():
    # prom.py:216-232's exact shape:
    # histogram_quantile(p, sum(rate(m[Ns])) by (g, le)) * 1000
    v = STORE.query(
        'histogram_quantile(0.5, sum(rate(h_bucket[180s])) '
        'by (service, le)) * 1000'
    )
    assert v[(("service", "a"),)] == pytest.approx(300.0)


def test_query_errors():
    with pytest.raises(QueryError):
        STORE.query("nosuchfn(m_total)")
    with pytest.raises(QueryError):
        STORE.query("m_total garbage")
    with pytest.raises(QueryError):
        STORE.query("m_total * gauge_bytes")  # vector*vector unsupported
    with pytest.raises(QueryError):
        # two series -> not a scalar
        STORE.query_value("sum(m_total) by (service)")


def test_histogram_quantile_empty_group_is_nan():
    s = MetricStore.from_text('e_bucket{le="+Inf"} 0\n', 1.0)
    assert math.isnan(
        s.query_value("histogram_quantile(0.9, e_bucket)")
    )


def test_histogram_quantile_single_inf_bucket_is_nan():
    # Prometheus needs at least one finite bucket + Inf
    s = MetricStore.from_text('e_bucket{le="+Inf"} 5\n', 1.0)
    assert math.isnan(
        s.query_value("histogram_quantile(0.9, e_bucket)")
    )
