"""Dashboard-lite report tests: renders from a sweep's results.jsonl,
regression deltas, chart/table structure."""
import json
import re

import pytest

from isotope_tpu import cli
from isotope_tpu.report import (
    build_report,
    regression_rows,
    svg_line_chart,
    write_report,
)


def fake_sweep(tmp_path, name, p99s, qps=1000):
    out = tmp_path / name
    out.mkdir()
    rows = []
    for env, per_env in p99s.items():
        for conns, p99 in per_env:
            rows.append(
                {
                    "Labels": f"topo_{env}_{qps}qps_{conns}c",
                    "StartTime": "2026-07-30T00:00:00+00:00",
                    "RequestedQPS": qps,
                    "ActualQPS": qps,
                    "NumThreads": conns,
                    "RunType": "HTTP",
                    "ActualDuration": 240,
                    "min": 2000,
                    "max": 9000,
                    "p50": p99 // 2,
                    "p75": int(p99 * 0.6),
                    "p90": int(p99 * 0.8),
                    "p99": p99,
                    "p999": int(p99 * 1.1),
                    "errorPercent": 0.0,
                    "windowDiscarded": False,
                    "cpu_cores_a": 0.1,
                    "cpu_cores_b": 0.2,
                }
            )
    with open(out / "results.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return out


SWEEP = {
    "baseline": [(2, 3000), (16, 3200), (64, 3600)],
    "both": [(2, 4200), (16, 4500), (64, 5100)],
}


def test_report_renders_charts_and_table(tmp_path):
    d = fake_sweep(tmp_path, "run1", SWEEP)
    out = tmp_path / "report.html"
    n = write_report(d, out)
    assert n == 6
    doc = out.read_text()
    assert doc.startswith("<!doctype html>")
    # charts: p50, p99, errors, cpu — each an svg
    assert doc.count("<svg") == 4
    assert "p99 vs connections" in doc
    assert "total service CPU vs connections" in doc
    # legend with both series, fixed slot colors in CSS
    assert "topo_baseline" in doc and "topo_both" in doc
    assert "#2a78d6" in doc and "#3987e5" in doc  # light + dark steps
    # table row per run
    assert doc.count("<tr") >= 7
    # native hover tooltips on the data points
    assert "<title>" in doc


def test_regression_view_flags_deltas(tmp_path):
    worse = {
        "baseline": [(2, 3000), (16, 3100), (64, 3500)],  # improved a bit
        "both": [(2, 5000), (16, 5600), (64, 6400)],      # >5% regressions
    }
    base = fake_sweep(tmp_path, "base", SWEEP)
    curdir = fake_sweep(tmp_path, "cur2", worse)
    out = tmp_path / "r.html"
    write_report(curdir, out, baseline_dir=base)
    doc = out.read_text()
    assert "Regression vs baseline" in doc
    assert 'class="regress"' in doc
    assert "+19.0%" in doc  # both/2c: 4200 -> 5000

    rows = regression_rows(
        [json.loads(line) for line in
         (curdir / "results.jsonl").read_text().splitlines()],
        [json.loads(line) for line in
         (base / "results.jsonl").read_text().splitlines()],
    )
    by_label = {r["label"]: r for r in rows}
    d = by_label["topo_both_1000qps_2c"]["metrics"]["p99"]
    assert d["delta"] == pytest.approx((5000 - 4200) / 4200)


def test_regression_direction_qps_down_is_worse():
    cur = [{"Labels": "x_1000qps_8c", "ActualQPS": 900, "NumThreads": 8,
            "p50": 100, "p90": 110, "p99": 120, "errorPercent": 0.0}]
    base = [{"Labels": "x_1000qps_8c", "ActualQPS": 1000, "NumThreads": 8,
             "p50": 100, "p90": 110, "p99": 120, "errorPercent": 0.0}]
    doc = build_report(cur, base)
    m = re.search(r'<td class="(\w+)"[^>]*>-10\.0%</td>', doc)
    assert m and m.group(1) == "regress"


def test_svg_chart_degenerate_inputs():
    assert svg_line_chart({}, "t", "x", "y") == ""
    one = svg_line_chart({"s": [(1.0, 5.0)]}, "t", "x", "y")
    assert "<svg" in one  # single point doesn't crash the scales
    # sub-1 spans still get a real tick scale (not a lone 0)
    small = svg_line_chart(
        {"s": [(1.0, 0.1), (2.0, 0.5)]}, "t", "x", "y"
    )
    ticks = re.findall(r'class="tick">([^<]+)', small)
    assert "0.2" in ticks or "0.25" in ticks


def test_regression_from_zero_baseline_is_flagged():
    cur = [{"Labels": "x_1000qps_8c", "ActualQPS": 1000, "NumThreads": 8,
            "p50": 100, "p90": 110, "p99": 120, "errorPercent": 8.0}]
    base = [{"Labels": "x_1000qps_8c", "ActualQPS": 1000, "NumThreads": 8,
             "p50": 100, "p90": 110, "p99": 120, "errorPercent": 0.0}]
    doc = build_report(cur, base)
    assert '<td class="regress" title="0 → 8">new</td>' in doc


def test_report_cli(tmp_path, capsys):
    d = fake_sweep(tmp_path, "run1", SWEEP)
    out = tmp_path / "rep.html"
    rc = cli.main(["report", str(d), "-o", str(out)])
    assert rc == 0
    assert out.exists()
    assert "6 runs" in capsys.readouterr().err


def test_report_missing_dir_errors(tmp_path):
    with pytest.raises(FileNotFoundError):
        write_report(tmp_path / "nosuch", tmp_path / "x.html")


# -- history across publish ids --------------------------------------------


def fake_publish(tmp_path, pid, p99):
    """One publish tree: <pid>/<config>/results.jsonl."""
    root = tmp_path / "pub"
    root.mkdir(exist_ok=True)
    tree = root / pid
    tree.mkdir()
    fake_sweep(tree, "latency", {"baseline": [(16, p99)]})
    return root


def test_history_report_over_publishes(tmp_path):
    from isotope_tpu.report import load_history, write_history_report

    for pid, p99 in (
        ("20260728_sim_master_dev", 3000),
        ("20260729_sim_master_dev", 3100),
        ("20260730_sim_master_dev", 3900),
    ):
        fake_publish(tmp_path, pid, p99)
    # a non-publish directory is ignored
    (tmp_path / "pub" / "scratch").mkdir()

    history = load_history(tmp_path / "pub")
    assert [pid for pid, _ in history] == [
        "20260728_sim_master_dev",
        "20260729_sim_master_dev",
        "20260730_sim_master_dev",
    ]

    out = tmp_path / "history.html"
    n = write_history_report(tmp_path / "pub", out)
    assert n == 3
    doc = out.read_text()
    # metric-over-publish charts with one series joined across ids
    assert "p99 over publishes" in doc
    assert "p50 over publishes" in doc
    assert "latency/topo_baseline" in doc
    # latest-vs-previous regression: p99 3100 -> 3900 is > 5% worse
    assert "Regression: 20260730_sim_master_dev vs" in doc
    assert "regress" in doc


def test_history_artifact_browser(tmp_path):
    # the reference dashboard also browses each publish's RAW
    # artifacts (perf_dashboard/artifacts/, helpers/download.py:27-66)
    # — the history report embeds a per-publish artifact listing with
    # links relative to the report's location
    from isotope_tpu.report import artifact_listing, write_history_report

    fake_publish(tmp_path, "20260730_sim_master_dev", 2500)
    files = artifact_listing(tmp_path / "pub" / "20260730_sim_master_dev")
    rels = [rel for rel, _ in files]
    assert any(r.endswith("results.jsonl") for r in rels)

    out = tmp_path / "history.html"
    write_history_report(tmp_path / "pub", out)
    doc = out.read_text()
    assert "<h2>Artifacts</h2>" in doc
    assert 'href="pub/20260730_sim_master_dev/' in doc
    assert "results.jsonl" in doc


def test_history_cli(tmp_path, capsys):
    fake_publish(tmp_path, "20260730_sim_master_dev", 2500)
    out = tmp_path / "h.html"
    rc = cli.main(
        ["report", str(tmp_path / "pub"), "--history", "-o", str(out)]
    )
    assert rc == 0
    assert "1 publishes" in capsys.readouterr().err
    assert "over publishes" in out.read_text()


def test_history_empty_root_errors(tmp_path):
    from isotope_tpu.report import load_history

    (tmp_path / "empty").mkdir()
    with pytest.raises(FileNotFoundError, match="no publish trees"):
        load_history(tmp_path / "empty")


def test_history_regression_joins_per_config(tmp_path):
    # the same run label in two configs must diff against ITS OWN
    # config's baseline, not whichever config won the label collision
    from isotope_tpu.report import build_history_report, load_history

    root = tmp_path / "pub"
    for pid, lat_p99, cpu_p99 in (
        ("20260729_sim_master_dev", 3000, 9000),
        ("20260730_sim_master_dev", 3100, 9100),
    ):
        tree = root / pid
        tree.mkdir(parents=True)
        fake_sweep(tree, "latency", {"baseline": [(16, lat_p99)]})
        fake_sweep(tree, "cpu_mem", {"baseline": [(16, cpu_p99)]})
    doc = build_history_report(load_history(root))
    # both joins are ~+1..3% (below the 5% band): nothing may be
    # flagged as a regression (a cross-config join would show +203%)
    assert "+203" not in doc
    assert 'class="regress"' not in doc
    assert "cpu_mem/topo_baseline" in doc


def test_history_mixed_lineages_require_selector(tmp_path):
    # same-date publishes from two loadgens must not be treated as one
    # timeline (the regression would diff open- vs closed-loop runs)
    from isotope_tpu.report import load_history

    root = tmp_path / "pub"
    for pid in (
        "20260730_fortio_master_dev",
        "20260730_nighthawk_master_dev",
    ):
        tree = root / pid
        tree.mkdir(parents=True)
        fake_sweep(tree, "latency", {"baseline": [(16, 3000)]})
    with pytest.raises(ValueError, match="2 publish lineages"):
        load_history(root)
    history = load_history(root, lineage="nighthawk")
    assert [pid for pid, _ in history] == ["20260730_nighthawk_master_dev"]
