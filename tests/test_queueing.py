"""Queueing model tests against textbook closed forms."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from isotope_tpu.sim import queueing


def test_erlang_b_known_values():
    # B(1, a) = a / (1 + a); B(2, a) = a*B1 / (2 + a*B1)
    a = jnp.asarray([0.5, 2.0])
    rows = queueing.erlang_b(a, 2)
    np.testing.assert_allclose(rows[0], [0.5 / 1.5, 2.0 / 3.0], rtol=1e-6)
    b1 = np.asarray([0.5 / 1.5, 2.0 / 3.0])
    np.testing.assert_allclose(
        rows[1], a * b1 / (2 + a * b1), rtol=1e-6
    )


def test_erlang_c_reduces_to_rho_for_single_server():
    # M/M/1: P(wait) = rho
    p = queueing.mmk_params(
        arrival_rate=jnp.asarray([300.0]),
        service_rate=jnp.asarray([1000.0]),
        replicas=jnp.asarray([1]),
        k_max=4,
    )
    np.testing.assert_allclose(p.p_wait, [0.3], rtol=1e-5)
    np.testing.assert_allclose(p.utilization, [0.3], rtol=1e-6)
    assert not bool(p.unstable[0])


def test_erlang_c_mm2_textbook():
    # M/M/2 with lambda=3, mu=2 => rho=0.75, C = 0.6428571...
    p = queueing.mmk_params(3.0, 2.0, jnp.asarray([2]), k_max=2)
    np.testing.assert_allclose(p.p_wait, 9.0 / 14.0, rtol=1e-5)
    np.testing.assert_allclose(p.wait_rate, 1.0, rtol=1e-5)


def test_unstable_station_flagged_and_clamped():
    p = queueing.mmk_params(2000.0, 1000.0, jnp.asarray([1]), k_max=1)
    assert bool(p.unstable[0])
    assert float(p.utilization[0]) == pytest.approx(2.0)
    assert float(p.wait_rate[0]) > 0  # clamped, still finite sampling


def test_sampled_mean_wait_matches_closed_form():
    lam, mu, k = 800.0, 1000.0, jnp.asarray([1])
    p = queueing.mmk_params(lam, mu, k, k_max=1)
    key = jax.random.PRNGKey(0)
    n = 200_000
    u = jax.random.uniform(key, (n,))
    e = jax.random.exponential(jax.random.fold_in(key, 1), (n,))
    waits = queueing.sample_wait(p, u, e)
    expected = float(queueing.mmk_mean_wait(lam, mu, k, k_max=1)[0])
    assert float(waits.mean()) == pytest.approx(expected, rel=0.02)


def test_mm1_sojourn_quantile():
    # mu - lambda = 200 => p50 = ln(2)/200
    q = queueing.mm1_sojourn_quantile(0.5, 800.0, 1000.0)
    assert float(q) == pytest.approx(np.log(2) / 200.0, rel=1e-5)

def test_conditional_wait_matches_two_tensor_sampler():
    # Same marginal as sample_wait: P(W=0) = 1 - p_wait, and conditional
    # on waiting the wait is Exp(wait_rate).
    lam, mu, k = 800.0, 1000.0, jnp.asarray([1])
    p = queueing.mmk_params(lam, mu, k, k_max=1)
    key = jax.random.PRNGKey(7)
    n = 200_000
    u = jax.random.uniform(key, (n,))
    waits = queueing.sample_wait_conditional(p.p_wait, p.wait_rate, u)
    frac_wait = float((waits > 0).mean())
    assert frac_wait == pytest.approx(float(p.p_wait[0]), abs=0.01)
    expected_mean = float(queueing.mmk_mean_wait(lam, mu, k, k_max=1)[0])
    assert float(waits.mean()) == pytest.approx(expected_mean, rel=0.02)
    # conditional mean given waiting = 1 / wait_rate
    cond = waits[waits > 0]
    assert float(cond.mean()) == pytest.approx(
        1.0 / float(p.wait_rate[0]), rel=0.02
    )


def test_conditional_wait_zero_p_wait_is_zero():
    w = queueing.sample_wait_conditional(
        jnp.asarray([0.0]), jnp.asarray([100.0]), jnp.asarray([0.5])
    )
    assert float(w[0]) == 0.0


def test_convolution_matches_mva_on_k1_networks():
    # the cross-check mva_load_dependent's docstring promises: on k=1
    # networks (where exact MVA is numerically sound) the stable Buzen
    # convolution must agree to float precision
    import numpy as np

    from isotope_tpu.sim import closed

    v = np.array([1.0, 0.6, 1.0])
    k = np.ones(3)
    lam_c, pi_c, pid_c = closed.convolution_marginals(
        v, k, 13000.0, 1.5e-3, 48
    )
    lam_m, pi_m, pid_m = closed.mva_load_dependent(
        v, v, k, 13000.0, 1.5e-3, 48
    )
    assert lam_c == pytest.approx(lam_m, rel=1e-9)
    np.testing.assert_allclose(
        pi_c, pi_m[:, : pi_c.shape[1]], atol=1e-9
    )
