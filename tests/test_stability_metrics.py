"""The shared stability-scenario metric contract.

Reference surface: perf/docker/prom_client.py:1-40 — every background
stability scenario (redis/rabbitmq/mysql clients, http10, bouncer)
reports ``stability_outgoing_requests_total{source, destination,
succeeded}`` plus a ``stability_test_instances{test}`` gauge, and the
alarm layer asserts on those series.  These tests pin the emitted
exposition, its queryability through the PromQL layer, the alarm
integration (including the running-query gate), and the
bounce-schedule coupling.
"""
import pytest

from isotope_tpu.metrics.alarms import run_queries
from isotope_tpu.metrics.query import MetricStore
from isotope_tpu.metrics.stability import (
    StabilityScenario,
    scenario_from_bounce,
    stability_queries,
    stability_text,
)


def test_counts_all_succeed():
    sc = StabilityScenario(name="redis", destination="redis-master",
                           period_s=1.0, success_prob=1.0)
    ok, fail = sc.counts(60.0)
    assert ok == 60 and fail == 0


def test_counts_failure_window():
    sc = StabilityScenario(
        name="http10", destination="httpbin", period_s=1.0,
        success_prob=1.0, fail_windows=((10.0, 20.0),),
    )
    ok, fail = sc.counts(60.0)
    assert fail == 10 and ok == 50


def test_counts_success_prob_binomial():
    sc = StabilityScenario(name="rabbitmq", destination="rabbitmq",
                           period_s=0.1, success_prob=0.7)
    ok, fail = sc.counts(600.0, seed=1)
    assert ok + fail == 6000
    assert 0.65 < ok / 6000 < 0.75


def test_exposition_schema():
    text = stability_text(
        [StabilityScenario(name="redis", destination="redis-master")],
        30.0,
    )
    assert "# TYPE stability_outgoing_requests_total counter" in text
    assert (
        'stability_outgoing_requests_total{source="redis",'
        'destination="redis-master",succeeded="True"} 30' in text
    )
    assert 'stability_test_instances{test="redis"} 1' in text


def test_queryable_and_alarm_clean():
    scenarios = [
        StabilityScenario(name="redis", destination="redis-master"),
        StabilityScenario(name="mysql", destination="mysql"),
    ]
    store = MetricStore.from_text(
        stability_text(scenarios, 120.0), 120.0
    )
    assert store.query_value(
        'sum(stability_outgoing_requests_total{succeeded="True"})'
    ) == pytest.approx(240.0)
    alarms = run_queries(
        stability_queries(scenarios), store, log=lambda s: None
    )
    assert alarms == []


def test_alarm_fires_on_failures():
    sc = StabilityScenario(
        name="http10", destination="httpbin",
        fail_windows=((0.0, 30.0),),
    )
    store = MetricStore.from_text(stability_text([sc], 120.0), 120.0)
    alarms = run_queries(
        stability_queries([sc]), store, log=lambda s: None
    )
    assert alarms and "http10" in alarms[0]


def test_running_query_gates_undeployed_scenario():
    # the store only carries redis; the mysql check must be SKIPPED
    # (running gauge absent), not fire a false alarm
    redis = StabilityScenario(name="redis", destination="redis-master")
    mysql = StabilityScenario(
        name="mysql", destination="mysql", fail_windows=((0.0, 60.0),),
    )
    store = MetricStore.from_text(stability_text([redis], 120.0), 120.0)
    alarms = run_queries(
        stability_queries([redis, mysql]), store, log=lambda s: None
    )
    assert alarms == []


def test_bounce_coupling():
    sc = scenario_from_bounce(
        "bouncer", "istio-ingressgateway",
        bounce_schedule=[(5.0, 10.0), (20.0, 25.0)],
    )
    ok, fail = sc.counts(30.0)
    assert fail == 10 and ok == 20
