"""int32/bf16 carry packing (SimParams.packed_carries).

The attribution sweep's COUNT-valued carries — request/tail counts,
per-hop crit/error counters, blame-histogram censuses — accumulate as
int32 when packed; crit weights are exact 0/1 products so the packing
is EXACT (not merely <= 1 ULP), and every seconds-valued accumulator
stays f32.  The bf16 half of the packing lives in the census kernel's
step mask (tests/test_census_pallas.py pins its exactness).
"""
import jax
import numpy as np
import pytest

from isotope_tpu.compiler import compile_graph
from isotope_tpu.models.graph import ServiceGraph
from isotope_tpu.sim.config import LoadModel, SimParams
from isotope_tpu.sim.engine import Simulator

KEY = jax.random.PRNGKey(0)
LOAD = LoadModel(kind="open", qps=200.0)

YAML = """
services:
- name: entry
  isEntrypoint: true
  errorRate: 2%
  script:
  - call: {service: mid, timeout: 30ms, retries: 2}
- name: mid
  errorRate: 5%
  script:
  - - call: leaf
    - call: {service: leaf2, probability: 60}
- name: leaf
  errorRate: 3%
- name: leaf2
"""


@pytest.fixture(scope="module")
def compiled():
    return compile_graph(ServiceGraph.from_yaml(YAML))


def _attr(compiled, packed, tail=False):
    sim = Simulator(
        compiled,
        SimParams(attribution=True, packed_carries=packed),
    )
    return sim.run_attributed(
        LOAD, 2048, KEY, block_size=512, tail=tail
    )


COUNT_FIELDS = (
    "count", "tail_count", "crit_count", "error_count",
    "tail_crit_count", "hist", "tail_hist",
)


@pytest.mark.slow
@pytest.mark.slow
@pytest.mark.parametrize("tail", [False, True])
def test_packed_equals_unpacked_exactly(compiled, tail):
    s1, a1 = _attr(compiled, packed=True, tail=tail)
    s2, a2 = _attr(compiled, packed=False, tail=tail)
    for f in a1._fields:
        if f == "exemplars":
            continue
        x = np.asarray(getattr(a1, f), np.float64)
        y = np.asarray(getattr(a2, f), np.float64)
        np.testing.assert_allclose(x, y, rtol=0, atol=0, err_msg=f)
    # the RunSummary half is untouched by the packing
    for f in s1._fields:
        if f == "metrics":
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f)),
            err_msg=f,
        )


@pytest.mark.slow
def test_packed_dtypes(compiled):
    _, a = _attr(compiled, packed=True, tail=True)
    for f in COUNT_FIELDS:
        assert np.asarray(getattr(a, f)).dtype == np.int32, f
    # seconds-valued accumulators stay f32 — the ULP pin forbids
    # narrowing them
    for f in ("wait_blame", "self_blame", "net_blame",
              "timeout_blame", "residual", "residual_abs",
              "tail_wait_blame"):
        assert np.asarray(getattr(a, f)).dtype == np.float32, f


@pytest.mark.slow
def test_packed_default_on(compiled):
    assert SimParams().packed_carries is True
    _, a = _attr(compiled, packed=True)
    assert np.asarray(a.count).dtype == np.int32


@pytest.mark.slow
@pytest.mark.slow
def test_packed_sharded_matches_emulated_twin(compiled):
    """int32 carries through the mesh psum stay bit-equal to the
    host-merged emulated twin (integer addition is associative)."""
    from isotope_tpu.parallel import ShardedSimulator, make_mesh

    sh = ShardedSimulator(
        compiled, make_mesh(4, 2), SimParams(attribution=True)
    )
    assert sh.sim.params.packed_carries
    s1, a1 = sh.run_attributed(LOAD, 4096, KEY, block_size=512)
    s2, a2 = sh.run_attributed_emulated(
        LOAD, 4096, KEY, block_size=512
    )
    for f in COUNT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a1, f)), np.asarray(getattr(a2, f)),
            err_msg=f,
        )
    assert float(s1.count) == float(s2.count)


def test_attribution_off_unaffected(compiled):
    """packed_carries touches only attributed programs: with
    attribution off the results are byte-identical either way."""
    r1 = Simulator(
        compiled, SimParams(packed_carries=True)
    ).run(LOAD, 1024, KEY)
    r2 = Simulator(
        compiled, SimParams(packed_carries=False)
    ).run(LOAD, 1024, KEY)
    for f in r1._fields:
        a, b = getattr(r1, f), getattr(r2, f)
        if a is None:
            assert b is None
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f
        )


def test_blame_doc_accepts_packed_counts(compiled):
    from isotope_tpu.metrics import attribution as attr_mod

    _, a = _attr(compiled, packed=True, tail=True)
    doc = attr_mod.to_doc(compiled, a)
    assert doc["count"] == 2048.0
    assert doc["services"] and abs(
        sum(r["share"] for r in doc["services"]) - 1.0
    ) < 1e-6
    assert doc["tail_count"] >= 1
    rows = attr_mod.service_blame(compiled, a, tail=True)
    assert rows
